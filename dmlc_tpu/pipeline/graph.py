"""Declarative dataset-pipeline graph → compiled, probed, tunable runs.

``Pipeline`` is the tf.data-style composition layer over the existing
machinery: chaining builds an immutable stage-spec tuple
(``dmlc_tpu.pipeline.stages``), ``build()`` lowers it onto
InputSplit / Parser / ThreadedIter / DiskRowIter / ShardedRowBlockIter —
nothing is reimplemented. Every stage boundary carries a
:class:`~dmlc_tpu.pipeline.stats.StageProbe` (wait time, rows/bytes,
queue occupancy) and every ``"auto"`` depth becomes an
:class:`~dmlc_tpu.pipeline.autotune.Knob` the between-epoch
:class:`~dmlc_tpu.pipeline.autotune.Autotuner` adjusts.

    pipe = (Pipeline.from_uri("train.libsvm", part_index=0, num_parts=1)
            .parse(format="libsvm")
            .batch(16384)
            .prefetch(depth="auto")
            .to_device(window="auto"))
    built = pipe.build(autotune=True)
    for epoch in range(epochs):
        for device_batch in built:          # one epoch
            step(device_batch)
        print(built.stats())                # per-stage snapshot
    built.close()

Ownership contract (the RowBlock lifetime rules, composed once here so
every stage agrees): a stage yields items valid until the consumer's
next pull. Buffering stages (``prefetch``) take ownership of ephemeral
native-engine blocks by detaching their arena lease (falling back to a
copy); ``to_device`` holds the lease until the async transfer lands —
the exact discipline bench.py hand-wired.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from dmlc_tpu.obs import trace as _trace
from dmlc_tpu.obs import watchdog as _watchdog
from dmlc_tpu.obs.metrics import REGISTRY as _METRICS
from dmlc_tpu.pipeline.autotune import Autotuner, Knob
from dmlc_tpu.pipeline.stages import StageSpec, validate_chain
from dmlc_tpu.pipeline.stats import StageProbe, snapshot
from dmlc_tpu.utils.logging import DMLCError, check

__all__ = ["Pipeline", "CompiledPipeline"]

_END = object()


def _probed(runner) -> Iterator:
    """Pull a runner's epoch through its probe: every boundary crossing
    records wait time, volume, and (when queue-backed) occupancy.

    Observability contract (tests/test_obs.py pins it): with a trace
    recorder active, every DELIVERED item emits exactly one complete
    span named ``pull/<stage>`` whose duration is the SAME perf_counter
    pair the probe accumulates into ``wait_s`` — so per-stage span
    totals and probe waits agree by construction (the terminal
    end-of-stream wait goes to ``pull/<stage>.end`` to keep the
    span-count == items invariant exact). Each pull also registers
    with the stall watchdog while it blocks."""
    gen = runner.epoch()
    probe = runner.probe
    pull_name = f"pull/{probe.name}"      # loop-invariant: built once,
    end_name = pull_name + ".end"         # not per delivered item
    while True:
        rec = _trace.active()
        token = _watchdog.begin_wait(pull_name, runner.wait_detail)
        t0 = time.perf_counter()
        try:
            item = next(gen, _END)
        finally:
            # a raising stage must not leave a phantom wait registered
            # — the watchdog would later report a stall that never was
            _watchdog.end_wait(token)
        dt = time.perf_counter() - t0
        if item is _END:
            probe.record_wait_only(dt)
            if rec is not None:
                rec.complete(end_name, t0, dt, "pipeline")
            return
        probe.record(item, dt, runner.queue)
        if rec is not None:
            rec.complete(pull_name, t0, dt, "pipeline")
        yield item


class _RunnerBase:
    """One lowered stage: re-enterable epochs + probe + optional knobs."""

    kind = "?"
    owned = True          # items survive past the consumer's next pull
    up: Optional["_RunnerBase"] = None

    def __init__(self, name: str):
        self.probe = StageProbe(name, self.kind)

    @property
    def queue(self):
        """Live bounded queue for occupancy sampling, or None."""
        return None

    def wait_detail(self) -> Dict[str, Any]:
        """Watchdog diagnosis sample for a blocked pull at this stage:
        queue state when queue-backed, plus stage extras (replay tier,
        serve stats) the runner recorded so far."""
        out: Dict[str, Any] = {"kind": self.kind,
                               "items": self.probe.items}
        q = self.queue
        if q is not None:
            try:
                out["qsize"] = q.qsize()
                out["capacity"] = q.capacity
            except Exception:  # noqa: BLE001 — diagnostics only
                pass
        if self.probe.extra:
            out["extra"] = dict(self.probe.extra)
        return out

    def epoch(self) -> Iterator:
        raise NotImplementedError

    def detach_last(self):
        """Take ownership of the last yielded item's arena lease
        (native engine); None when items are already owned."""
        return None

    def knobs(self) -> List[Knob]:
        return []

    def finalize_epoch(self) -> None:
        """Stage-specific snapshot extras (engine stats, drain waits)."""

    def close(self) -> None:
        pass


def _finalize_parser(parser, probe) -> None:
    """Stamp a parser's end-of-epoch telemetry into ``probe``: engine
    stats (+ counter track), the native span-ring drain onto the active
    timeline, and bytes_read. Shared by _ParseRunner and the fused
    _NativeAssembleRunner so both stages report the engine the same
    way."""
    stats_fn = getattr(parser, "stats", None)
    if stats_fn is not None:
        try:
            engine = stats_fn()
            probe.extra["engine"] = engine
            # native-engine counters as a trace counter track: the
            # reader/parse busy split rides next to the spans
            _trace.counter("engine", engine, "native")
        except Exception:  # noqa: BLE001 — telemetry must not kill
            pass
    rec = _trace.active()
    drain = getattr(parser, "drain_trace", None)
    if rec is not None and drain is not None:
        # the engine's span ring (chunk read/tokenize/assemble/
        # cache events) joins the Python spans on ONE timeline
        try:
            drain(rec)
        except Exception:  # noqa: BLE001 — telemetry must not kill
            pass
    try:
        probe.extra["bytes_read"] = int(parser.bytes_read())
    except Exception:  # noqa: BLE001
        pass
    # which decode path served the epoch (parquet: pyarrow golden vs
    # the ABI-8 native page decoder) — obs/analyze's decode evidence
    # names it with its measured GB/s, so a config-5-shaped DECODE-
    # bound verdict says WHICH decoder was the wall
    dp = getattr(parser, "decode_path", None)
    if dp:
        probe.extra["decode_path"] = dp


class _ParseRunner(_RunnerBase):
    """source [+ shuffle] + parse → Parser.create (native or python)."""

    kind = "parse"

    def __init__(self, source: StageSpec, shuffle: Optional[StageSpec],
                 parse: StageSpec):
        super().__init__("parse")
        sp = source.params
        p = dict(parse.params)
        fmt = p.pop("format", None)
        depth = p.pop("prefetch_depth", "auto")
        self._auto_depth = depth == "auto"
        kwargs = {k: v for k, v in p.items() if v is not None}
        self._stream_split = None
        stream = sp.get("stream")
        if stream is not None:
            # streaming source (Pipeline.from_stream): an EOF-less
            # windowed split injected under the python engine (the
            # native reader owns its own split, and a growing file has
            # no frozen byte range for it to own)
            from dmlc_tpu.io.streaming_split import StreamingSplit
            kwargs["engine"] = "python"
            split = StreamingSplit(sp["uri"], **stream)
            self._stream_split = split
            kwargs["split_factory"] = lambda: split
        if shuffle is not None:
            # shuffled read order lowers to an injected split under the
            # python engine (the native reader owns its own split):
            # global_seed → the sample-level GlobalShuffleSplit, else
            # the chunk-level InputSplitShuffle
            kwargs["engine"] = "python"
            chunk = kwargs.get("chunk_size", 8 << 20)
            shp = shuffle.params

            if shp.get("global_seed") is not None:
                from dmlc_tpu.shuffle.split import GlobalShuffleSplit

                def factory():
                    from dmlc_tpu.shuffle.exchange import \
                        DEFAULT_WINDOW_BYTES
                    wb = shp.get("window_bytes") or DEFAULT_WINDOW_BYTES
                    return GlobalShuffleSplit(
                        sp["uri"], sp["part_index"], sp["num_parts"],
                        sp["split_type"], seed=shp["global_seed"],
                        window_bytes=wb)
            else:
                from dmlc_tpu.io.input_split_shuffle import \
                    InputSplitShuffle

                def factory():
                    return InputSplitShuffle.create(
                        sp["uri"], sp["part_index"], sp["num_parts"],
                        sp["split_type"],
                        num_shuffle_parts=shp["num_shuffle_parts"],
                        seed=shp["seed"], chunk_size=chunk)

            kwargs["split_factory"] = factory
        if sp["split_type"] != "text":
            # non-default record framing reaches TextParserBase; the
            # native engine (text reader only) declines it and "auto"
            # falls back to the python golden
            kwargs["split_type"] = sp["split_type"]
        from dmlc_tpu.data.parser import Parser
        self._parser = Parser.create(
            sp["uri"], sp["part_index"], sp["num_parts"], format=fmt,
            prefetch_depth=4 if self._auto_depth else int(depth), **kwargs)
        self.owned = not hasattr(self._parser, "detach")
        if self._stream_split is not None:
            # formats whose parser ignores split_factory (parquet's
            # param struct swallows unknown keys) would silently read
            # the frozen file instead of the stream — refuse (the
            # shuffle-injection precedent below)
            if getattr(self._parser, "_split", None) \
                    is not self._stream_split:
                raise DMLCError(
                    f"pipeline: from_stream is not supported by the "
                    f"{fmt or 'default'} parser (it ignores the "
                    "injected split); streaming works with record-"
                    "stream formats (libsvm/csv/libfm)")
        if shuffle is not None:
            # formats whose parser ignores split_factory (parquet's
            # param struct swallows unknown keys) would silently yield
            # UNshuffled data — refuse instead
            from dmlc_tpu.io.input_split_shuffle import InputSplitShuffle
            from dmlc_tpu.shuffle.split import GlobalShuffleSplit
            split = getattr(self._parser, "_split", None)
            wants = (shuffle.params.get("global_seed") is not None
                     or shuffle.params["num_shuffle_parts"] > 1)
            if (wants and not isinstance(
                    split, (InputSplitShuffle, GlobalShuffleSplit))):
                raise DMLCError(
                    f"pipeline: shuffle is not supported by the "
                    f"{fmt or 'default'} parser (it ignores the "
                    "injected split); shuffle works with record-stream "
                    "formats (libsvm/csv/libfm)")

    @property
    def queue(self):
        return getattr(self._parser, "_prefetch", None)

    def epoch(self) -> Iterator:
        p = self._parser
        p.before_first()
        while p.next():
            yield p.value()

    def detach_last(self):
        detach = getattr(self._parser, "detach", None)
        return detach() if detach is not None else None

    def knobs(self) -> List[Knob]:
        ti = self.queue
        if self._auto_depth and ti is not None:
            return [Knob("parse.chunk_prefetch", "parse",
                         lambda: ti.capacity, ti.set_capacity,
                         lo=1, hi=32)]
        return []

    def finalize_epoch(self) -> None:
        _finalize_parser(self._parser, self.probe)
        if self._stream_split is not None:
            # the monotonic watermark rides the stage extras (and the
            # scheduler's /tenants rows read it live mid-epoch)
            self.probe.extra["stream"] = self._stream_split.watermark()

    def close(self) -> None:
        if self._stream_split is not None:
            self._stream_split.stop()
        if hasattr(self._parser, "destroy"):
            self._parser.destroy()


class _CacheRunner(_RunnerBase):
    """parse + cache → replayed epochs with no re-parse, the tier
    picked by budget (the ShardedRowBlockIter steady-replay story at
    the single-stream level): blocks whose raw bytes fit
    ``memory_budget_bytes`` are retained owned in RAM; larger datasets
    build a DiskRowIter binary page cache (parse once at build, replay
    pages every epoch). An explicit ``path`` forces the page tier (the
    pre-r6 contract); with ``path=None`` the page file is derived under
    the spill dir, fingerprint-keyed so a changed source gets a fresh
    cache, with a sidecar meta for sweep_stale_spill."""

    kind = "cache"

    def __init__(self, source: StageSpec, shuffle: Optional[StageSpec],
                 parse: StageSpec, cache: StageSpec):
        super().__init__("cache")
        check(shuffle is None,
              "pipeline: shuffle + cache is not lowerable (the page "
              "cache replays one fixed order); shuffle after cache via "
              "a map stage, or drop the cache")
        from dmlc_tpu.data.row_iter import DiskRowIter
        sp = source.params
        self._source_uri = sp["uri"]
        self._source_parts = sp["num_parts"]
        p = {k: v for k, v in parse.params.items() if v is not None}
        p.pop("prefetch_depth", None)
        fmt = p.pop("format", None)
        if sp["split_type"] != "text":
            p["split_type"] = sp["split_type"]

        def make_parser():
            from dmlc_tpu.data.parser import Parser
            return Parser.create(sp["uri"], sp["part_index"],
                                 sp["num_parts"], format=fmt, **p)

        path = cache.params["path"]
        budget = cache.params.get("memory_budget_bytes")
        if budget is None:  # not `or`: an explicit 0 must force pages
            budget = 1 << 30
        self._blocks: Optional[List] = None
        self._it = None
        self.tier = "pages"
        fingerprint = self._source_fingerprint(sp)
        if path is None:
            if self._try_memory(make_parser, budget):
                self.tier = "memory"
                return
            path = self._derived_page_path(
                sp, fmt, cache.params["rows_per_page"], fingerprint)
        page_budget = cache.params.get("page_budget_bytes")
        if page_budget is not None:
            # the store owning this cache's root gets the byte budget
            # (LRU eviction of cold committed entries down to it)
            from dmlc_tpu.io.pagestore import PageStore
            PageStore.for_path(path)[0].set_budget(page_budget)
        # DiskRowIter stamps the sidecar itself at commit (and a
        # stamped cache whose sources changed is rebuilt, not replayed)
        self._it = DiskRowIter(make_parser, path,
                               rows_per_page=cache.params["rows_per_page"],
                               fingerprint=fingerprint)

    def _try_memory(self, make_parser, budget: int) -> bool:
        """Drain the parser into owned raw blocks within the budget;
        False (with nothing retained) when the dataset is larger — the
        caller then builds the page tier from a fresh parser. A stat
        pre-check skips the doomed drain outright when the source's
        byte share already exceeds the budget (raw CSR is rarely
        smaller than its text — the same reasoning as
        ShardedRowBlockIter._cache_precheck_ok), so a 10 GB source
        does not parse 1 GiB twice."""
        if not self._memory_precheck_ok(budget):
            return False
        parser = make_parser()
        blocks: List = []
        used = 0
        ok = True
        parser.before_first()
        while parser.next():
            blk = parser.value()
            if getattr(blk, "lease", None) is not None:
                blk = blk.copy()  # own past the parser's next()
            used += blk.memory_cost_bytes()
            if used > budget:
                ok = False
                break
            blocks.append(blk)
        if hasattr(parser, "destroy"):
            parser.destroy()
        if ok:
            self._blocks = blocks
        return ok

    def _memory_precheck_ok(self, budget: int) -> bool:
        try:
            from dmlc_tpu.io.input_split import list_split_files
            total = sum(size for _, size in
                        list_split_files(self._source_uri))
            share = total // max(self._source_parts, 1)
            return share <= budget
        except Exception:  # noqa: BLE001 — non-stat-able: try the drain
            return True

    @staticmethod
    def _source_fingerprint(sp):
        """``[[path, size, mtime_ns], ...]`` of the source's backing
        files, stat'ed through the FileSystem seam (remote ``obj://``
        sources stamp too), or None when non-stat-able."""
        try:
            from dmlc_tpu.io.input_split import list_split_files
            from dmlc_tpu.io.pagestore import stat_fingerprint
            return stat_fingerprint(
                p for p, _ in list_split_files(sp["uri"]))
        except Exception:  # noqa: BLE001 — non-stat-able source
            return None

    @staticmethod
    def _derived_page_path(sp, fmt, rows_per_page: int, fingerprint):
        """Page path under the default store root — fingerprint-keyed
        so a changed source derives a fresh cache file (the stamp
        DiskRowIter writes catches in-place mutation of an unchanged
        name too)."""
        import hashlib
        import os as _os

        from dmlc_tpu.io.pagestore import default_store_dir
        key = hashlib.sha256(repr(
            (sp["uri"], sp["part_index"], sp["num_parts"], fmt,
             rows_per_page, fingerprint)).encode()).hexdigest()[:16]
        d = default_store_dir()
        _os.makedirs(d, exist_ok=True)
        return _os.path.join(d, f"cache-{key}.pages")

    @property
    def queue(self):
        return getattr(self._it, "_iter", None)

    def epoch(self) -> Iterator:
        if self._blocks is not None:
            yield from self._blocks
            return
        it = self._it
        it.before_first()
        while it.next():
            yield it.value()

    def finalize_epoch(self) -> None:
        # which replay tier served the epoch — the autotuner must not
        # judge a knob trial across a tier flip, and bench JSON readers
        # need to know which regime a number came from
        self.probe.extra["replay_tier"] = self.tier

    def close(self) -> None:
        self._blocks = None
        if self._it is not None:
            self._it._close()


class _ShardRunner(_RunnerBase):
    """source [+ parse opts] + shard → ShardedRowBlockIter global
    batches ([D, ...] jax.Arrays on the mesh's data axis)."""

    kind = "shard"

    def __init__(self, source: StageSpec, parse: Optional[StageSpec],
                 shard: StageSpec):
        super().__init__("shard")
        from dmlc_tpu.parallel.sharded import ShardedRowBlockIter
        sp = source.params
        p = dict(parse.params) if parse is not None else {}
        p.pop("prefetch_depth", None)
        fmt = p.pop("format", None)
        p = {k: v for k, v in p.items() if v is not None}
        if sp["split_type"] != "text":
            p["split_type"] = sp["split_type"]
        shp = dict(shard.params)
        mesh = shp.pop("mesh")
        self._it = ShardedRowBlockIter(sp["uri"], mesh, format=fmt,
                                       **shp, **p)

    def epoch(self) -> Iterator:
        return iter(self._it)

    @property
    def queue(self):
        # the live serve ThreadedIter while an epoch runs: occupancy
        # samples land in the probe, which is what lets the autotuner
        # actually drive the shard.prefetch knob (before r6 the shard
        # stage had no queue telemetry, so the knob never moved)
        return getattr(self._it, "_serve_queue", None)

    def knobs(self) -> List[Knob]:
        it = self._it

        def _set(n: int) -> None:
            it.prefetch_depth = n

        return [Knob("shard.prefetch", "shard",
                     lambda: it.prefetch_depth, _set, lo=1, hi=8)]

    def finalize_epoch(self) -> None:
        it = self._it
        tier = getattr(it, "replay_tier", None)
        if tier is not None:
            self.probe.extra["replay_tier"] = tier
        self.probe.extra["replay_epochs"] = getattr(it, "replay_epochs", 0)
        self.probe.extra["page_replay_epochs"] = getattr(
            it, "page_replay_epochs", 0)
        serve = getattr(it, "_serve_stats", None)
        if serve:
            self.probe.extra["serve"] = dict(serve)

    def close(self) -> None:
        close = getattr(self._it, "close", None)
        if close is not None:
            close()


class _BatchRunner(_RunnerBase):
    """Re-chunk the block stream to fixed row counts (owned output)."""

    kind = "batch"

    def __init__(self, up: _RunnerBase, rows: int, drop_remainder: bool):
        super().__init__("batch")
        check(rows >= 1, "batch(rows) needs rows >= 1")
        self.up = up
        self._rows = rows
        self._drop = drop_remainder

    def epoch(self) -> Iterator:
        from dmlc_tpu.data.rowblock import RowBlockContainer
        pending: Optional[RowBlockContainer] = None
        for block in _probed(self.up):
            if pending is None:
                pending = RowBlockContainer(block.index.dtype)
            start = 0
            while start < block.size:
                take = min(block.size - start, self._rows - pending.size)
                pending.push_block(block.slice(start, start + take))
                start += take
                if pending.size >= self._rows:
                    yield pending.get_block()
                    pending = RowBlockContainer(block.index.dtype)
        if pending is not None and pending.size and not self._drop:
            yield pending.get_block()


class _PadBatchRunner(_RunnerBase):
    """Padded batch assembly, the Python fused golden: re-chunk the
    block stream to ``rows`` rows, then pad each batch to
    (row_bucket, nnz_bucket) device layout in ONE pass
    (data.padding.pad_single). Output dicts own their arrays. This is
    the fallback — and the byte-parity reference — for the native
    ABI-5 path (_NativeAssembleRunner); tests pin the two equal."""

    kind = "assemble"

    def __init__(self, up: _RunnerBase, spec: StageSpec):
        super().__init__("assemble")
        p = spec.params
        self.up = up
        self._rows = p["rows"]
        self._drop = p["drop_remainder"]
        self._row_bucket = p["row_bucket"] or p["rows"]
        self._nnz_bucket = p["nnz_bucket"]
        self._want_qid = p["want_qid"]
        self._want_field = p["want_field"]
        check(self._rows >= 1, "batch(rows) needs rows >= 1")
        check(self._row_bucket >= self._rows,
              "batch(row_bucket) must be >= rows")
        self._assemble_s = 0.0

    def epoch(self) -> Iterator:
        from dmlc_tpu.data.padding import pad_single
        from dmlc_tpu.data.rowblock import RowBlockContainer
        self._assemble_s = 0.0

        def cut(pending):
            t0 = time.perf_counter()
            padded = pad_single(pending.get_block(), self._row_bucket,
                                self._nnz_bucket, self._want_qid,
                                self._want_field)
            self._assemble_s += time.perf_counter() - t0
            return padded

        pending = None
        for block in _probed(self.up):
            if pending is None:
                pending = RowBlockContainer(block.index.dtype)
            start = 0
            while start < block.size:
                take = min(block.size - start, self._rows - pending.size)
                pending.push_block(block.slice(start, start + take))
                start += take
                if pending.size >= self._rows:
                    yield cut(pending)
                    pending = RowBlockContainer(block.index.dtype)
        if pending is not None and pending.size and not self._drop:
            yield cut(pending)

    def finalize_epoch(self) -> None:
        # which rung assembled the epoch's batches — bench attributes
        # wins to native-padded vs python-fused with this field
        self.probe.extra["assembly_path"] = "python-fused"
        self.probe.extra["assemble_s"] = round(self._assemble_s, 6)


class _NativeAssembleRunner(_RunnerBase):
    """source + parse + batch(pad=True) fused onto the native engine's
    ABI-5 batch assembly: ``dtp_parser_next_padded`` emits bucket-
    padded, device-layout blocks directly from the parse arena — the
    pad+stack memcpy runs in C with the GIL released and Python never
    touches row bytes on this path. Each yielded PaddedBatch is a dict
    of ZERO-COPY views into a leased padded block (valid until the next
    pull — the standard RowBlock lifetime contract; downstream
    prefetch/to_device detach the lease exactly as they do for CSR
    leases). Byte parity with _PadBatchRunner is pinned by
    tests/test_native_assembly.py."""

    kind = "assemble"
    owned = False  # items are leased engine views

    def __init__(self, parse_runner: "_ParseRunner", spec: StageSpec):
        super().__init__("assemble")
        # take over the already-constructed parser (and its close/stats
        # surface); the parse stage folds into this one
        self._parser = parse_runner._parser
        p = spec.params
        self._rows = p["rows"]
        self._drop = p["drop_remainder"]
        self._row_bucket = p["row_bucket"] or p["rows"]
        self._nnz_bucket = p["nnz_bucket"]
        self._want_qid = p["want_qid"]
        self._want_field = p["want_field"]
        check(self._row_bucket >= self._rows,
              "batch(row_bucket) must be >= rows")

    def epoch(self) -> Iterator:
        p = self._parser
        p.before_first()
        while True:
            batch = p.next_padded(self._rows, self._row_bucket,
                                  self._nnz_bucket, self._want_qid,
                                  self._want_field)
            if batch is None:
                return
            if self._drop and int(batch["num_rows"]) < self._rows:
                continue  # short tail at end of stream
            yield batch

    def detach_last(self):
        return self._parser.detach()

    def finalize_epoch(self) -> None:
        _finalize_parser(self._parser, self.probe)
        self.probe.extra["assembly_path"] = "native-padded"
        eng = self.probe.extra.get("engine") or {}
        if eng.get("assemble_ns") is not None:
            # consumer-side pad+stack memcpy time, measured in the
            # engine (queue waits excluded) — comparable to the python
            # path's assemble_s
            self.probe.extra["assemble_s"] = round(
                eng["assemble_ns"] / 1e9, 6)

    def close(self) -> None:
        if hasattr(self._parser, "destroy"):
            self._parser.destroy()


class _MapRunner(_RunnerBase):
    """User fn over each item. The fn sees the upstream item under the
    upstream's lifetime contract; ownership passes through unchanged."""

    kind = "map"

    def __init__(self, up: _RunnerBase, fn: Callable, name: str):
        super().__init__(name)
        self.up = up
        self._fn = fn
        self.owned = up.owned  # lifetime contract passes through

    def epoch(self) -> Iterator:
        fn = self._fn
        for item in _probed(self.up):
            yield fn(item)

    def detach_last(self):
        return self.up.detach_last()


class _PrefetchRunner(_RunnerBase):
    """Bounded background queue (ThreadedIter). Converts ephemeral
    upstream items to owned ones: the producer thread detaches each
    native arena lease (or copies), and the consumer releases a lease
    when the NEXT item is pulled — preserving the valid-until-next-pull
    contract downstream."""

    kind = "prefetch"

    def __init__(self, up: _RunnerBase, depth):
        super().__init__("prefetch")
        self.up = up
        self._auto = depth == "auto"
        from dmlc_tpu.data.threaded_iter import ThreadedIter
        self._ti = ThreadedIter(
            max_capacity=4 if self._auto else int(depth),
            name="prefetch")
        self._src: Optional[Iterator] = None
        self._started = False

    @property
    def queue(self):
        return self._ti

    def _restart(self) -> None:
        gen = _probed(self.up)
        if self.up.owned:
            self._src = gen
            return

        def owning():
            for item in gen:
                lease = self.up.detach_last()
                if lease is not None:
                    item.lease = lease
                else:
                    item = item.copy()
                yield item

        self._src = owning()

    def epoch(self) -> Iterator:
        if not self._started:
            self._restart()
            self._ti.init(lambda: next(self._src, None), self._restart)
            self._started = True
        else:
            self._ti.before_first()
        prev = None

        def release_prev():
            if prev is not None and getattr(prev, "lease", None) is not None:
                prev.lease.release()
                prev.lease = None

        try:
            while True:
                item = self._ti.next()
                release_prev()
                if item is None:
                    return
                prev = item  # before the yield: an abandoned epoch's
                yield item   # finally must release the CURRENT item too
        finally:
            release_prev()  # the epoch's last lease (or an abandon)

    def knobs(self) -> List[Knob]:
        if not self._auto:
            return []
        return [Knob("prefetch.depth", "prefetch",
                     lambda: self._ti.capacity, self._ti.set_capacity,
                     lo=1, hi=64)]

    def finalize_epoch(self) -> None:
        if self._started:
            # epoch-scoped producer counters (reset on before_first):
            # blocked-on-full-queue time tells consumer-bound from
            # producer-bound without inferring it from occupancy alone
            self.probe.extra["producer"] = self._ti.stats()

    def close(self) -> None:
        self._ti.destroy()


class _DeviceRunner(_RunnerBase):
    """Async host→device transfers with a bounded in-flight window —
    the parse-to-HBM discipline bench.py hand-wired: device_put is
    enqueued immediately, the arena lease (native engine) is held until
    that transfer is drained, and ``window`` transfers ride under the
    upstream's work."""

    kind = "to_device"

    def __init__(self, up: _RunnerBase, device, sharding, window,
                 staging="auto"):
        super().__init__("to_device")
        self.up = up
        self._auto = window == "auto"
        self.window = 4 if self._auto else int(window)
        check(self.window >= 1, "to_device(window) needs window >= 1")
        check(device is None or sharding is None,
              "to_device: pass device OR sharding, not both")
        self._target = sharding if sharding is not None else device
        # staging: route batches through a reusable host staging pair
        # (parallel.device_iter.HostStaging) so the source buffers are
        # free at COPY time and the H2D transfer of batch N overlaps
        # batch N+1's assembly. "auto" = on for dict batches (the
        # fixed-shape padded steady path, where slot reuse pays), off
        # for RowBlock streams (variable shapes defeat the pool).
        check(staging in (True, False, "auto"),
              "to_device(staging) must be True, False or 'auto'")
        self._staging = staging

    @staticmethod
    def _host_arrays(item) -> Dict[str, np.ndarray]:
        if isinstance(item, dict):
            return item
        out = {"offset": item.offset, "label": item.label,
               "index": item.index}
        for k in ("value", "weight", "qid", "field"):
            v = getattr(item, k)
            if v is not None:
                out[k] = v
        return out

    def _platform(self) -> str:
        import jax
        t = self._target
        if t is None:
            return jax.default_backend()
        if hasattr(t, "platform"):       # a Device
            return t.platform
        devs = getattr(t, "device_set", None)  # a Sharding
        if devs:
            return next(iter(devs)).platform
        return jax.default_backend()

    def epoch(self) -> Iterator:
        import jax

        from dmlc_tpu.parallel.device_iter import HostStaging
        target = self._target
        put = (jax.device_put if target is None
               else (lambda x: jax.device_put(x, target)))
        cpu_backend = self._platform() == "cpu"
        # staging pool built lazily at the first dict item under "auto":
        # one pool per epoch, window+1 slots (window in flight + one
        # being staged), no reuse on the aliasing CPU backend
        pool: Optional[HostStaging] = None
        if self._staging is True:
            pool = HostStaging(self.window + 1, alias_unsafe=cpu_backend)
        in_flight: deque = deque()
        xfer_wait = 0.0

        def drain_one():
            nonlocal xfer_wait
            fut, lease, slot, t_enq = in_flight.popleft()
            t0 = time.perf_counter()
            jax.block_until_ready(fut)
            now = time.perf_counter()
            dt = now - t0
            xfer_wait += dt
            self.probe.extra["xfer_wait_s"] = round(xfer_wait, 6)
            rec = _trace.active()
            if rec is not None:
                rec.complete("to_device.drain", t0, dt, "transfer",
                             {"in_flight": len(in_flight) + 1})
                if slot is not None:
                    # the full async window, enqueue → ready: it
                    # overlaps the NEXT batch's device.assemble span —
                    # the Perfetto-visible proof the double-buffer works
                    rec.complete("device.xfer", t_enq, now - t_enq,
                                 "transfer")
            if lease is not None:
                lease.release()
            if slot is not None:
                pool.release(slot)
            return fut

        for item in _probed(self.up):
            if self.up.owned:
                # an OWNED item may still carry a detached arena lease
                # (prefetch over a native parse): take it over so the
                # upstream's release-on-next-pull cannot return the
                # arena while this async transfer is in flight
                lease = getattr(item, "lease", None)
                if lease is not None:
                    item.lease = None
            else:
                lease = self.up.detach_last()
            arrs = self._host_arrays(item)
            if pool is None and self._staging == "auto" \
                    and isinstance(item, dict):
                pool = HostStaging(self.window + 1,
                                   alias_unsafe=cpu_backend)
            slot = None
            if pool is not None:
                # staged path: one copy into the reusable slot frees
                # the source NOW — a leased padded block returns to the
                # engine pool while its bytes are still in flight
                slot = pool.stage(arrs)
                arrs = slot
                if lease is not None:
                    lease.release()
                    lease = None
                self.probe.extra["staging_assemble_s"] = round(
                    pool.assemble_s, 6)
            elif lease is not None and cpu_backend:
                # the CPU-aliasing rule (io/tpu_fs._device_put_safe):
                # CPU-backend device_put may ALIAS host memory, and a
                # leased arena gets recycled after release — copy now
                # and free the arena immediately. Real accelerator
                # transfers copy, keeping the zero-copy fast path.
                arrs = {k: np.array(v, copy=True) for k, v in arrs.items()}
                lease.release()
                lease = None
            fut = put(arrs)
            in_flight.append((fut, lease, slot, time.perf_counter()))
            # window is re-read each round: the autotuner adjusts it
            # between epochs (and a mid-epoch change is simply honored)
            while len(in_flight) > self.window:
                yield drain_one()
        while in_flight:
            yield drain_one()

    def knobs(self) -> List[Knob]:
        if not self._auto:
            return []

        def _set(n: int) -> None:
            self.window = n

        return [Knob("device.window", "to_device",
                     lambda: self.window, _set, lo=1, hi=32)]


class CompiledPipeline:
    """Executable form of a Pipeline: iterate for one epoch, read
    ``stats()``, let the bound autotuner retune depths between epochs."""

    def __init__(self, runners: List[_RunnerBase],
                 autotuner: Optional[Autotuner],
                 tenant: Optional[str] = None):
        self._runners = runners
        self.autotuner = autotuner
        # multi-tenant contract (pipeline.scheduler): the tenant this
        # pipeline bills its pulls to, and the queue-capacity knobs
        # the scheduler owns (the autotuner/controller must not move
        # them — one owner per knob)
        self.tenant = tenant
        self.scheduler_owned: tuple = ()
        self._epoch = 0
        self._last: Optional[Dict[str, Any]] = None
        # one-way hand-off flag: a controller that raised on this
        # pipeline never gets it back (see the epoch hook below)
        self._control_failed = False
        # the pipeline's stats() registers as an obs metrics collector:
        # one REGISTRY.snapshot() sees the last epoch's stage stats
        # next to queue/engine/profiler surfaces (docs/observability.md)
        self._metrics_key = _METRICS.register(
            "pipeline", self, CompiledPipeline._last_snapshot)

    def _last_snapshot(self) -> Optional[Dict[str, Any]]:
        return self._last

    # -- iteration

    def __iter__(self) -> Iterator:
        """One epoch. At a COMPLETE epoch the stats snapshot is frozen
        and the autotuner (if bound) takes its between-epoch step; an
        abandoned epoch leaves the previous snapshot in place."""
        for r in self._runners:
            r.probe.reset()
        sched = None
        if self.tenant is not None:
            from dmlc_tpu.pipeline import scheduler as _sched
            sched = _sched.active()
        t0 = time.perf_counter()
        if sched is None:
            yield from _probed(self._runners[-1])
        else:
            # multi-tenant discipline: every delivered batch costs one
            # pull credit FIRST (a credit-blocked tenant stops pulling
            # — its bounded queues fill and the throttle propagates up
            # to its readers), then bills its latency + volume to the
            # tenant's accounting. The billed latency is the
            # tenant-EXPERIENCED wait — credit wait included — because
            # that is what a declared latency SLO (obs.slo) judges: a
            # credit-starved tenant is missing its objective even when
            # its pipeline produces instantly
            from dmlc_tpu.pipeline.stats import _item_stats
            gen = _probed(self._runners[-1])
            while True:
                tb = time.perf_counter()
                sched.acquire(self.tenant)
                item = next(gen, _END)
                if item is _END:
                    break
                rows, _nnz, nbytes = _item_stats(item)
                sched.note_batch(self.tenant,
                                 time.perf_counter() - tb,
                                 rows=rows, nbytes=nbytes)
                yield item
        wall = time.perf_counter() - t0
        for r in self._runners:
            r.finalize_epoch()
        self._epoch += 1
        self._last = snapshot([r.probe for r in self._runners], wall,
                              self._epoch, self.knob_values())
        if self.tenant is not None:
            # the tenant label rides the snapshot (obs/analyze emits
            # per-tenant bound verdicts from it; /tenants rows cite it)
            self._last["tenant"] = self.tenant
        if sched is not None:
            sched.note_epoch(self.tenant, self._last)
        # one mover per process: an installed verdict-driven
        # controller (obs.control) adopts this pipeline's knobs and
        # subsumes the blind hill-climber — the bound verdict picks
        # WHICH family moves; otherwise the bound autotuner takes its
        # between-epoch step as before
        ctl = None
        if not self._control_failed:
            try:
                from dmlc_tpu.obs import control as _control
                ctl = _control.active()
            except Exception:  # noqa: BLE001 — telemetry never kills
                ctl = None
        if ctl is not None:
            if self.autotuner is not None \
                    and self.autotuner.rail.pending is not None:
                # a controller installed MID-RUN takes over from the
                # autotuner: its in-flight trial would never be judged
                # again — discard it (value restored, no freeze) so no
                # knob is stranded at an unjudged trial value
                self.autotuner.rail.discard()
            try:
                ctl.observe_pipeline(self, self._last)
            except Exception as e:  # noqa: BLE001 — a controller bug
                # must not take down the epoch loop, and it must not
                # SILENTLY disable tuning either. The hand-off is
                # ONE-WAY (this pipeline never returns to the
                # controller): alternating movers would let the
                # autotuner arm a trial the controller's epoch never
                # resolves — a knob stranded at an unjudged value
                from dmlc_tpu.obs.log import warn_limited
                warn_limited(
                    "control-observe-failed",
                    f"obs.control: observe_pipeline failed ({e!r}); "
                    "this pipeline falls back to its own autotuner "
                    "permanently",
                    min_interval_s=60)
                self._control_failed = True
                try:
                    # release this pipeline's controller state: an
                    # unresolved pending trial would wedge every
                    # OTHER source into no-ops, and the controller
                    # must stop moving knobs the autotuner now owns
                    ctl.abandon_pipeline(self)
                except Exception:  # noqa: BLE001
                    pass
                ctl = None
        if ctl is None and self.autotuner is not None:
            self.autotuner.after_epoch(self._last)

    def run_epoch(self) -> Dict[str, Any]:
        """Drain one epoch and return its stats snapshot."""
        for _ in self:
            pass
        assert self._last is not None
        return self._last

    # -- telemetry / tuning

    def stats(self) -> Optional[Dict[str, Any]]:
        """Snapshot of the last COMPLETE epoch (None before the first)."""
        return self._last

    def knobs(self) -> List[Knob]:
        return [k for r in self._runners for k in r.knobs()]

    def knob_values(self) -> Dict[str, int]:
        return {k.name: k.get() for k in self.knobs()}

    def autotune_report(self) -> Optional[Dict[str, Any]]:
        return (self.autotuner.report()
                if self.autotuner is not None else None)

    def trace(self, path: str, capacity: int = 1 << 20):
        """Record a Chrome/Perfetto trace of everything run inside the
        block and export it to ``path`` on exit::

            with built.trace("epoch.json"):
                for batch in built:
                    step(batch)

        Every stage pull becomes a ``pull/<stage>`` span, queue waits
        and transfer drains appear on their own threads, and native
        engine counters ride as counter tracks (dmlc_tpu.obs.trace;
        installs the global recorder for the duration)."""
        return _trace.trace_to(path, capacity)

    def stream_stats(self) -> Optional[Dict[str, Any]]:
        """Live watermark of a streaming source (None for finite
        pipelines) — readable MID-epoch, unlike stats()."""
        src = self._runners[0]
        split = getattr(src, "_stream_split", None)
        return split.watermark() if split is not None else None

    @property
    def epochs(self) -> int:
        return self._epoch

    def close(self) -> None:
        if self.tenant is not None:
            try:
                from dmlc_tpu.pipeline import scheduler as _sched
                sched = _sched.active()
                if sched is not None:
                    sched.release(self)
            except Exception:  # noqa: BLE001 — teardown must not fail
                pass
        if self._metrics_key is not None:
            _METRICS.unregister(self._metrics_key)
            self._metrics_key = None
        for r in reversed(self._runners):
            r.close()

    def __enter__(self) -> "CompiledPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class Pipeline:
    """Immutable declarative stage chain; see the module docstring."""

    __slots__ = ("_stages",)

    def __init__(self, stages: Tuple[StageSpec, ...]):
        self._stages = stages

    # -- construction

    @staticmethod
    def from_uri(uri: str, part_index: int = 0, num_parts: int = 1,
                 split_type: str = "text") -> "Pipeline":
        """Root of every pipeline: one shard of a (multi-file) URI —
        the InputSplit sharding contract."""
        check(0 <= part_index < num_parts,
              f"part_index {part_index} out of range for {num_parts}")
        return Pipeline((StageSpec("source", uri=uri,
                                   part_index=part_index,
                                   num_parts=num_parts,
                                   split_type=split_type),))

    @staticmethod
    def from_stream(uri: str, *, window_records: Optional[int] = None,
                    window_s: Optional[float] = None,
                    poll_interval_s: float = 0.05,
                    idle_timeout_s: Optional[float] = None,
                    chunk_size: int = 8 << 20) -> "Pipeline":
        """Root of a STREAMING pipeline: an EOF-less windowed read of
        one growing text source (:class:`dmlc_tpu.io.streaming_split.
        StreamingSplit`). Appended records accumulate into windows
        closed by ``window_records`` and/or ``window_s``; each window
        feeds the unchanged parse/batch/to_device machinery, with a
        monotonic watermark in the parse stage's ``extra["stream"]``.
        The epoch ends when the split's ``stop()`` is called and the
        committed bytes are drained, or after ``idle_timeout_s`` with
        no growth (None = stream forever). Streaming sources are
        single-part and cannot be shuffled, cached, or sharded (the
        chain validator rejects those stages)."""
        return Pipeline((StageSpec(
            "source", uri=uri, part_index=0, num_parts=1,
            split_type="text",
            stream={"window_records": window_records,
                    "window_s": window_s,
                    "poll_interval_s": poll_interval_s,
                    "idle_timeout_s": idle_timeout_s,
                    "chunk_size": chunk_size}),))

    def _with(self, spec: StageSpec) -> "Pipeline":
        return Pipeline(self._stages + (spec,))

    def parse(self, format: Optional[str] = None, engine: str = "auto",
              chunk_size: int = 8 << 20, nthreads: Optional[int] = None,
              index_dtype=np.uint32, prefetch_depth="auto",
              **kwargs: Any) -> "Pipeline":
        """Bytes → CSR RowBlock stream (Parser.create; format kwargs
        such as label_column pass through). prefetch_depth="auto" makes
        the python engine's chunk-prefetch queue an autotuner knob."""
        return self._with(StageSpec("parse", format=format, engine=engine,
                                    chunk_size=chunk_size,
                                    nthreads=nthreads,
                                    index_dtype=index_dtype,
                                    prefetch_depth=prefetch_depth,
                                    **kwargs))

    def shuffle(self, num_shuffle_parts: int = 4, seed: int = 0,
                global_seed: Optional[int] = None,
                window_bytes: Optional[int] = None) -> "Pipeline":
        """Shuffled read order. Default: chunk-level
        (InputSplitShuffle) — the shard subdivides into
        num_shuffle_parts sub-shards whose order reshuffles each
        epoch, deterministically from ``seed``.

        ``global_seed`` switches to the gang-wide SAMPLE-level shuffle
        (dmlc_tpu.shuffle.GlobalShuffleSplit): a seeded global
        permutation over every record of the dataset, identical at any
        world size, window-bounded to ``window_bytes`` resident bytes
        (default dmlc_tpu.shuffle.DEFAULT_WINDOW_BYTES), with window
        pages exchanged through the peer /pages tier."""
        check(num_shuffle_parts >= 1, "num_shuffle_parts must be >= 1")
        check(window_bytes is None or window_bytes > 0,
              "shuffle: window_bytes must be > 0")
        check(window_bytes is None or global_seed is not None,
              "shuffle: window_bytes applies to the global shuffle — "
              "pass global_seed")
        return self._with(StageSpec("shuffle",
                                    num_shuffle_parts=num_shuffle_parts,
                                    seed=seed, global_seed=global_seed,
                                    window_bytes=window_bytes))

    def cache(self, path: Optional[str] = None,
              rows_per_page: int = 64 << 10,
              memory_budget_bytes: Optional[int] = None,
              page_budget_bytes: Optional[int] = None) -> "Pipeline":
        """Parse once; later epochs replay instead of re-parsing text.
        The tier is picked by budget (default 1 GiB; an explicit 0
        forces pages): raw blocks within ``memory_budget_bytes`` are
        retained in RAM, larger datasets spill to binary row pages
        (DiskRowIter) under the unified page store, fingerprint-keyed
        AND fingerprint-stamped (a changed source rebuilds instead of
        replaying). An explicit ``path`` forces the page tier at that
        location. ``page_budget_bytes`` sets the owning page store's
        byte budget: committed entries LRU-evict down to it (pinned
        live caches are skipped) — the on-disk analogue of
        ``memory_budget_bytes``.

        The memory tier serves the SAME RowBlock objects every epoch —
        RowBlock is immutable by contract, so downstream ``map`` fns
        must not mutate blocks in place (true of every stage, but here
        a violation corrupts all later epochs instead of one)."""
        return self._with(StageSpec("cache", path=path,
                                    rows_per_page=rows_per_page,
                                    memory_budget_bytes=memory_budget_bytes,
                                    page_budget_bytes=page_budget_bytes))

    def batch(self, rows: int, drop_remainder: bool = False,
              pad: bool = False, row_bucket: Optional[int] = None,
              nnz_bucket: Optional[int] = None, want_qid: bool = False,
              want_field: bool = False) -> "Pipeline":
        """Re-chunk the block stream to exactly ``rows`` rows per block
        (last partial block kept unless drop_remainder).

        ``pad=True`` (or passing ``nnz_bucket``) switches the stage to
        PADDED batch assembly: each batch is a fixed-shape,
        device-layout dict padded to (row_bucket, nnz_bucket) — the
        data.padding layout contract (offset/label/weight/index/value
        + num_rows/num_nnz, optional qid/field). ``row_bucket``
        defaults to ``rows``; ``nnz_bucket`` is required (it bounds the
        batch's nnz — a batch that exceeds it raises). When the stage
        sits directly on a native-engine parse, assembly lowers onto
        the engine's ABI-5 ``dtp_parser_next_padded`` (zero-copy leased
        views, Python never touches row bytes); otherwise the Python
        fused golden pads — byte-identical, pinned. The lowering that
        ran is reported as ``assembly_path`` in the stage stats."""
        pad = pad or nnz_bucket is not None
        if pad:
            check(nnz_bucket is not None,
                  "batch(pad=True) needs nnz_bucket (the padded batch's "
                  "fixed nnz capacity)")
            check(row_bucket is None or row_bucket >= rows,
                  "batch(row_bucket) must be >= rows")
        return self._with(StageSpec("batch", rows=rows,
                                    drop_remainder=drop_remainder,
                                    pad=pad, row_bucket=row_bucket,
                                    nnz_bucket=nnz_bucket,
                                    want_qid=want_qid,
                                    want_field=want_field))

    def map(self, fn: Callable, name: Optional[str] = None) -> "Pipeline":
        """Apply ``fn`` to every item. ``fn`` sees items under the
        upstream lifetime contract (copy before retaining ephemeral
        native blocks)."""
        return self._with(StageSpec("map", fn=fn, name=name or "map"))

    def prefetch(self, depth="auto") -> "Pipeline":
        """Decouple producer and consumer with a bounded background
        queue; depth="auto" is an autotuner knob."""
        return self._with(StageSpec("prefetch", depth=depth))

    def shard(self, mesh, axis: str = "data", row_bucket: int = 1 << 14,
              nnz_bucket: int = 1 << 18, **kwargs: Any) -> "Pipeline":
        """Device-granular multi-host ingest: lowers source+parse into
        ShardedRowBlockIter and yields global [D, ...] jax.Array batch
        dicts sharded on the mesh's ``axis``."""
        return self._with(StageSpec("shard", mesh=mesh, axis=axis,
                                    row_bucket=row_bucket,
                                    nnz_bucket=nnz_bucket, **kwargs))

    def to_device(self, device=None, sharding=None,
                  window="auto", staging="auto") -> "Pipeline":
        """Async host→device transfers, ``window`` in flight;
        window="auto" is an autotuner knob. ``staging`` routes batches
        through a reusable host staging pair (copy frees the source
        immediately; transfer N overlaps assembly N+1, proven by
        device.assemble/device.xfer spans and the device.staging
        gauge): True, False, or "auto" (on for dict batches — the
        fixed-shape padded steady path — off for RowBlock streams)."""
        return self._with(StageSpec("to_device", device=device,
                                    sharding=sharding, window=window,
                                    staging=staging))

    # -- compilation

    def build(self, autotune: bool = False,
              tenant: Optional[str] = None,
              **autotune_opts: Any) -> CompiledPipeline:
        """Validate the chain and lower it onto the existing iterator
        machinery. ``autotune=True`` binds an Autotuner over every
        "auto" depth knob (no-op when the chain has none).

        ``tenant`` admits the compiled pipeline under that tenant of
        the installed :mod:`dmlc_tpu.pipeline.scheduler` (admission
        control applies — past the tenant's budget this RAISES
        AdmissionError or queues, per the tenant's policy). Every
        delivered batch then costs one scheduler pull credit, volume
        and latency bill to the tenant, and the scheduler owns the
        pipeline's queue-capacity knobs (withheld from the autotuner
        here — one owner per knob)."""
        specs = self._stages
        validate_chain(specs)
        kinds = [s.kind for s in specs]
        if "parse" not in kinds and "shard" not in kinds:
            raise DMLCError(
                "pipeline: nothing to run — add .parse(...) or "
                ".shard(mesh)")
        source = specs[0]
        i = 1
        shuffle_spec = None
        parse_spec = None
        if i < len(specs) and specs[i].kind == "shuffle":
            shuffle_spec = specs[i]
            i += 1
        if i < len(specs) and specs[i].kind == "parse":
            parse_spec = specs[i]
            i += 1
        runners: List[_RunnerBase] = []
        if i < len(specs) and specs[i].kind == "cache":
            runners.append(_CacheRunner(source, shuffle_spec, parse_spec,
                                        specs[i]))
            i += 1
        elif i < len(specs) and specs[i].kind == "shard":
            runners.append(_ShardRunner(source, parse_spec, specs[i]))
            i += 1
        else:
            runners.append(_ParseRunner(source, shuffle_spec, parse_spec))
        for spec in specs[i:]:
            up = runners[-1]
            if spec.kind == "batch" and spec.params.get("pad"):
                # padded assembly sitting DIRECTLY on a native-engine
                # parse fuses into the engine's batch assembly (ABI-5
                # single parser, or the ABI-6 gang for a sharded
                # parse — NativeShardedTextParser.next_padded); anything
                # else (python engine, cache/shuffle upstream, map
                # between) pads through the Python fused golden —
                # byte-identical by the pinned contract
                if (len(runners) == 1 and isinstance(up, _ParseRunner)
                        and hasattr(up._parser, "next_padded")):
                    runners[-1] = _NativeAssembleRunner(up, spec)
                else:
                    runners.append(_PadBatchRunner(up, spec))
            elif spec.kind == "batch":
                runners.append(_BatchRunner(up, spec.params["rows"],
                                            spec.params["drop_remainder"]))
            elif spec.kind == "map":
                runners.append(_MapRunner(up, spec.params["fn"],
                                          spec.params["name"]))
            elif spec.kind == "prefetch":
                runners.append(_PrefetchRunner(up, spec.params["depth"]))
            elif spec.kind == "to_device":
                runners.append(_DeviceRunner(
                    up, spec.params["device"], spec.params["sharding"],
                    spec.params["window"],
                    spec.params.get("staging", "auto")))
            else:  # pragma: no cover — validate_chain rejects these
                raise DMLCError(f"pipeline: unexpected stage {spec.kind!r}")
        sched = None
        owned: tuple = ()
        if tenant is not None:
            from dmlc_tpu.pipeline import scheduler as _sched
            sched = _sched.active()
            if sched is None:
                raise DMLCError(
                    "pipeline: build(tenant=...) needs an installed "
                    "scheduler (dmlc_tpu.pipeline.scheduler.install() "
                    f"or {_sched.ENV_SCHED}=1)")
            owned = _sched.MANAGED_KNOBS
        tuner = None
        if autotune:
            knobs = [k for r in runners for k in r.knobs()
                     if k.name not in owned]
            if knobs:
                tuner = Autotuner(knobs, **autotune_opts)
        built = CompiledPipeline(runners, tuner, tenant=tenant)
        built.scheduler_owned = owned
        if sched is not None:
            try:
                sched.admit(tenant, built)
            except Exception:
                built.close()  # free the runners a failed admission
                raise          # would otherwise leak
        return built

    # -- introspection

    @property
    def stages(self) -> Tuple[StageSpec, ...]:
        return self._stages

    def __repr__(self) -> str:
        return "Pipeline(" + " → ".join(map(repr, self._stages)) + ")"
