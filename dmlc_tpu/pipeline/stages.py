"""Declarative stage specs for the dataset-pipeline graph.

A :class:`~dmlc_tpu.pipeline.Pipeline` is an immutable tuple of
``StageSpec`` values; chaining (``.parse().batch(...)``) appends specs
without executing anything. ``Pipeline.build()`` validates the chain
against ``ALLOWED_AFTER`` (the legal stage grammar) and lowers each spec
onto the existing machinery — InputSplit/Parser/ThreadedIter/DiskRowIter/
ShardedRowBlockIter — rather than reimplementing it (see
``dmlc_tpu.pipeline.graph``).

Stage catalog (docs/pipeline.md has the narrative version):

  source    — from_uri(uri, part_index, num_parts): the sharded byte span
  shuffle   — shuffled read order, python engine. Default: chunk-level
              (InputSplitShuffle, reference: input_split_shuffle.h);
              with global_seed: gang-wide sample-level global
              permutation (dmlc_tpu.shuffle.GlobalShuffleSplit),
              window-bounded, exchanged via the peer /pages tier
  parse     — text/columnar bytes → CSR RowBlock stream (Parser.create)
  cache     — parse once, replay later epochs; the tier is picked by
              memory_budget_bytes: raw blocks in RAM when they fit,
              a DiskRowIter binary page cache when they don't
              (an explicit path forces pages)
  batch     — re-chunk the block stream to fixed row counts
  map       — user fn over each item
  prefetch  — bounded background queue (ThreadedIter); depth "auto" is
              an autotuner knob
  shard     — device-granular multi-host ingest to global jax.Arrays
              (ShardedRowBlockIter)
  to_device — async host→device transfers with a bounded in-flight
              window; window "auto" is an autotuner knob
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

from dmlc_tpu.utils.logging import DMLCError, check

__all__ = ["StageSpec", "ALLOWED_AFTER", "validate_chain"]


class StageSpec:
    """One immutable node of the declarative graph."""

    __slots__ = ("kind", "params")

    def __init__(self, kind: str, **params: Any):
        self.kind = kind
        self.params: Dict[str, Any] = params

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}={v!r}" for k, v in self.params.items()
                          if v is not None)
        return f"{self.kind}({inner})"


# stage grammar: which stage kinds may follow which. "item" marks the
# transforming stages legal over any materialized item stream.
_ITEM_STAGES = ("batch", "map", "prefetch", "to_device")

ALLOWED_AFTER: Dict[str, Tuple[str, ...]] = {
    "source": ("shuffle", "parse", "shard"),
    "shuffle": ("parse",),
    "parse": ("cache", "shard") + _ITEM_STAGES,
    "cache": _ITEM_STAGES,
    "batch": _ITEM_STAGES,
    "map": _ITEM_STAGES,
    "prefetch": _ITEM_STAGES,
    "shard": ("map", "prefetch"),
    "to_device": (),  # terminal
}


def validate_chain(stages: Tuple[StageSpec, ...]) -> None:
    """Raise DMLCError on an illegal chain, naming the violation."""
    check(len(stages) > 0, "empty pipeline")
    check(stages[0].kind == "source",
          f"pipeline must start at from_uri(), got {stages[0].kind!r}")
    for prev, cur in zip(stages, stages[1:]):
        allowed = ALLOWED_AFTER[prev.kind]
        if cur.kind not in allowed:
            raise DMLCError(
                f"pipeline: {cur.kind!r} cannot follow {prev.kind!r} "
                f"(allowed after {prev.kind}: {sorted(allowed)})")
    kinds = [s.kind for s in stages]
    for unique in ("parse", "shard", "cache", "to_device"):
        if kinds.count(unique) > 1:
            raise DMLCError(f"pipeline: {unique!r} may appear only once")
    if "shard" in kinds:
        # shard lowers source+parse into ShardedRowBlockIter itself:
        # nothing may transform the block stream before it
        pre = kinds[:kinds.index("shard")]
        for k in pre:
            if k not in ("source", "parse"):
                raise DMLCError(
                    f"pipeline: {k!r} before shard is not lowerable — "
                    "shard compiles source+parse directly into "
                    "ShardedRowBlockIter")
    if stages[0].params.get("stream") is not None:
        # a streaming source has no frozen byte range: nothing that
        # needs one (re-read shuffle order, replay caches, byte-range
        # shards) can sit on it
        for k in ("shuffle", "cache", "shard"):
            if k in kinds:
                raise DMLCError(
                    f"pipeline: {k!r} is not lowerable over a "
                    "streaming source (from_stream) — a growing file "
                    "has no frozen byte range to "
                    + ("reshuffle" if k == "shuffle" else
                       "replay" if k == "cache" else "shard"))
    if "shuffle" in kinds:
        i = kinds.index("shuffle")
        if i + 1 < len(kinds) and kinds[i + 1] == "parse":
            eng = stages[i + 1].params.get("engine", "auto")
            if eng == "native":
                raise DMLCError(
                    "pipeline: shuffle requires the python parse engine "
                    "(the native reader owns its own split); drop "
                    "engine='native'")
