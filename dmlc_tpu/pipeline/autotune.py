"""Between-epoch depth autotuning from stage telemetry.

Replaces the hard-coded queue depths the hand-wired stacks carried
(``ThreadedIter(max_capacity=4)``, the bench loop's fixed ``> 4``
in-flight device window, ``depth(chunkq=3, reorder=2)`` in BENCH logs)
with measured decisions: after every completed epoch the tuner reads the
pipeline's stats snapshot (``dmlc_tpu.pipeline.stats``) and adjusts at
most ONE knob, then watches the next epoch's throughput to keep or
revert the change.

Model (deliberately simple — one trial per epoch keeps every decision
attributable):

- A queue whose mean occupancy is near its capacity is *producer-ahead*:
  the producer fills it and blocks. Growing it lets the producer run
  further ahead and absorbs consumer bursts → trial ``depth *= 2``.
- A queue that is near-empty while its consumer still waits on it is
  *producer-bound*: depth cannot help; a near-empty queue with NO
  consumer wait is over-provisioned → trial ``depth //= 2`` (memory
  thrift).
- A windowed transfer stage (``to_device``) whose transfer-drain wait
  dominates grows its in-flight window.
- Any trial whose next-epoch throughput drops below
  ``revert_tolerance`` × the best accepted throughput is reverted and
  the knob is frozen for ``cooldown`` epochs.
- Stages that replay (cache/shard) stamp ``extra.replay_tier``
  ("parse" | "memory" | "pages") into their snapshot; when the tier
  serving an epoch CHANGES (e.g. a re-parse epoch after a mutation, or
  the first page-replay epoch — regimes ~5× apart in throughput), the
  pending trial is discarded (knob restored, no freeze) and the best-
  throughput reference resets, so a knob is never credited or blamed
  for a tier flip.

Convergence: knob values are clamped to [lo, hi] and every accept/revert
is recorded in ``report()`` — on a steady workload the tuner reaches a
fixed point (tests/test_pipeline.py pins this on a synthetic slow
stage).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from dmlc_tpu.utils.logging import check

__all__ = ["Knob", "Autotuner"]


class Knob:
    """One tunable integer depth bound to a live pipeline object."""

    __slots__ = ("name", "stage", "get", "set", "lo", "hi", "initial",
                 "frozen_until")

    def __init__(self, name: str, stage: str, get: Callable[[], int],
                 set: Callable[[int], None], lo: int, hi: int):
        check(lo >= 1 and hi >= lo, f"knob {name}: bad bounds [{lo},{hi}]")
        self.name = name
        self.stage = stage  # probe name whose telemetry drives this knob
        self.get = get
        self.set = set
        self.lo = lo
        self.hi = hi
        self.initial = get()
        self.frozen_until = 0  # epoch index gate after a revert


class Autotuner:
    """One-trial-per-epoch hill climber over pipeline depth knobs."""

    def __init__(self, knobs: List[Knob], *,
                 grow_occupancy: float = 0.7,
                 shrink_occupancy: float = 0.15,
                 wait_frac_floor: float = 0.05,
                 revert_tolerance: float = 0.9,
                 cooldown: int = 3):
        self.knobs = list(knobs)
        self.grow_occupancy = grow_occupancy
        self.shrink_occupancy = shrink_occupancy
        self.wait_frac_floor = wait_frac_floor
        self.revert_tolerance = revert_tolerance
        self.cooldown = cooldown
        self._epoch = 0
        self._best_tp: Optional[float] = None
        self._pending: Optional[Dict[str, Any]] = None
        self._log: List[Dict[str, Any]] = []
        self._tier_sig: Optional[tuple] = None  # last epoch's replay
        # tiers per stage — a change resets the throughput reference

    # -- helpers

    @staticmethod
    def _throughput(snapshot: Dict[str, Any]) -> float:
        """Epoch objective: sink-stage bytes/s (falls back to items/s
        ×1.0 when the sink reports no bytes — same ordering either
        way)."""
        wall = snapshot.get("wall_s") or 0.0
        if wall <= 0:
            return 0.0
        stages = snapshot.get("stages") or []
        if not stages:
            return 0.0
        sink = stages[-1]
        vol = sink.get("bytes") or sink.get("items") or 0
        return vol / wall

    @staticmethod
    def _stage(snapshot: Dict[str, Any], name: str) -> Optional[Dict]:
        for s in snapshot.get("stages", []):
            if s.get("name") == name:
                return s
        return None

    @staticmethod
    def _tier_signature(snapshot: Dict[str, Any]) -> tuple:
        """(stage, replay_tier) pairs for every tier-stamped stage —
        empty for pipelines without replaying stages, so the tier gate
        below never fires for them."""
        return tuple(
            (s.get("name"), (s.get("extra") or {}).get("replay_tier"))
            for s in snapshot.get("stages") or []
            if (s.get("extra") or {}).get("replay_tier"))

    def _resolve_pending(self, tp: float) -> None:
        trial = self._pending
        self._pending = None
        assert trial is not None
        knob = trial["knob"]
        if (self._best_tp is not None
                and tp < self.revert_tolerance * self._best_tp):
            knob.set(trial["old"])
            knob.frozen_until = self._epoch + self.cooldown
            trial["outcome"] = "reverted"
        else:
            trial["outcome"] = "accepted"
            if self._best_tp is None or tp > self._best_tp:
                self._best_tp = tp
        trial["throughput"] = round(tp, 2)
        self._log.append({k: v for k, v in trial.items() if k != "knob"})

    def _propose(self, snapshot: Dict[str, Any]) -> None:
        for knob in self.knobs:
            if self._epoch < knob.frozen_until:
                continue
            stage = self._stage(snapshot, knob.stage)
            if stage is None:
                continue
            cur = knob.get()
            new = None
            reason = None
            occ = stage.get("queue_occupancy")
            if occ is not None:
                if occ >= self.grow_occupancy and cur < knob.hi:
                    new = min(cur * 2, knob.hi)
                    reason = f"occupancy {occ:.2f} ≥ {self.grow_occupancy}"
                elif (occ <= self.shrink_occupancy and cur > knob.lo
                      and (stage.get("wait_frac") or 0.0)
                      <= self.wait_frac_floor):
                    new = max(cur // 2, knob.lo)
                    reason = (f"occupancy {occ:.2f} ≤ "
                              f"{self.shrink_occupancy}, idle consumer")
            else:
                # windowed stage (to_device): grow while its drain wait
                # dominates the epoch
                extra = stage.get("extra") or {}
                xfer = extra.get("xfer_wait_s")
                wall = snapshot.get("wall_s") or 0.0
                if (xfer is not None and wall > 0
                        and xfer / wall > self.wait_frac_floor
                        and cur < knob.hi):
                    new = min(cur * 2, knob.hi)
                    reason = f"xfer wait {xfer / wall:.2f} of epoch"
            if new is not None and new != cur:
                knob.set(new)
                self._pending = {"knob": knob, "name": knob.name,
                                 "epoch": self._epoch, "old": cur,
                                 "new": new, "reason": reason}
                return  # one trial per epoch

    # -- public API

    def after_epoch(self, snapshot: Dict[str, Any]) -> None:
        """Feed one completed epoch's stats; may adjust one knob."""
        tp = self._throughput(snapshot)
        sig = self._tier_signature(snapshot)
        if self._tier_sig is not None and sig != self._tier_sig:
            # the serving tier flipped under this epoch: throughput is
            # a different regime (page replay vs parse differ ~5×), so
            # neither judge the pending trial by it nor let it set the
            # best-throughput reference
            self._best_tp = None
            if self._pending is not None:
                trial = self._pending
                self._pending = None
                trial["knob"].set(trial["old"])
                trial["outcome"] = "discarded (replay tier changed)"
                trial["throughput"] = round(tp, 2)
                self._log.append({k: v for k, v in trial.items()
                                  if k != "knob"})
        self._tier_sig = sig
        if self._pending is not None:
            self._resolve_pending(tp)
        elif self._best_tp is None or tp > self._best_tp:
            self._best_tp = tp
        self._propose(snapshot)
        self._epoch += 1

    def values(self) -> Dict[str, int]:
        return {k.name: k.get() for k in self.knobs}

    def tuned(self) -> Dict[str, int]:
        """Knobs whose current value differs from their initial one —
        the 'set by the autotuner rather than a constant' evidence."""
        return {k.name: k.get() for k in self.knobs
                if k.get() != k.initial}

    def converged(self, last_n: int = 3) -> bool:
        """No accepted change in the last ``last_n`` decisions (or no
        decisions at all and no trial pending)."""
        if self._pending is not None:
            return False
        recent = self._log[-last_n:]
        return all(d["outcome"] != "accepted" for d in recent) \
            if recent else self._epoch >= last_n

    def report(self) -> Dict[str, Any]:
        return {
            "epochs": self._epoch,
            "values": self.values(),
            "initial": {k.name: k.initial for k in self.knobs},
            "tuned": self.tuned(),
            "decisions": list(self._log),
            "best_throughput": (round(self._best_tp, 2)
                                if self._best_tp is not None else None),
        }
