"""Between-epoch depth autotuning from stage telemetry.

Replaces the hard-coded queue depths the hand-wired stacks carried
(``ThreadedIter(max_capacity=4)``, the bench loop's fixed ``> 4``
in-flight device window, ``depth(chunkq=3, reorder=2)`` in BENCH logs)
with measured decisions: after every completed epoch the tuner reads the
pipeline's stats snapshot (``dmlc_tpu.pipeline.stats``) and adjusts at
most ONE knob, then watches the next epoch's throughput to keep or
revert the change.

Model (deliberately simple — one trial per epoch keeps every decision
attributable):

- A queue whose mean occupancy is near its capacity is *producer-ahead*:
  the producer fills it and blocks. Growing it lets the producer run
  further ahead and absorbs consumer bursts → trial ``depth *= 2``.
- A queue that is near-empty while its consumer still waits on it is
  *producer-bound*: depth cannot help; a near-empty queue with NO
  consumer wait is over-provisioned → trial ``depth //= 2`` (memory
  thrift).
- A windowed transfer stage (``to_device``) whose transfer-drain wait
  dominates grows its in-flight window.
- Any trial whose next-epoch throughput drops below
  ``revert_tolerance`` × the best accepted throughput is reverted and
  the knob is frozen for ``cooldown`` epochs. The reverted epoch's
  stats were measured under the BAD knob value, so they neither set
  the throughput reference nor seed the next trial — proposing from
  them double-counted the regression into the following decision.
- Stages that replay (cache/shard) stamp ``extra.replay_tier``
  ("parse" | "memory" | "pages") into their snapshot; when the tier
  serving an epoch CHANGES (e.g. a re-parse epoch after a mutation, or
  the first page-replay epoch — regimes ~5× apart in throughput), the
  pending trial is discarded (knob restored, no freeze) and the best-
  throughput reference resets, so a knob is never credited or blamed
  for a tier flip.

The accept/revert/cooldown machinery itself is :class:`ExplorationRail`
— the safe-exploration rails shared with the verdict-driven controller
(:mod:`dmlc_tpu.obs.control`), which generalizes them with per-family
revert budgets. The Autotuner keeps the local per-knob heuristics; the
controller owns the global "WHICH family" judgment (the bound verdict).

Convergence: knob values are clamped to [lo, hi] and every accept/revert
is recorded in ``report()`` — on a steady workload the tuner reaches a
fixed point (tests/test_pipeline.py pins this on a synthetic slow
stage).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from dmlc_tpu.utils.logging import check

__all__ = ["Knob", "Autotuner", "ExplorationRail", "epoch_throughput",
           "tier_signature"]


def epoch_throughput(snapshot: Dict[str, Any]) -> float:
    """Epoch objective: sink-stage bytes/s (falls back to items/s
    ×1.0 when the sink reports no bytes — same ordering either
    way). The ONE throughput definition every exploration decision
    (Autotuner and controller alike) is judged by."""
    wall = snapshot.get("wall_s") or 0.0
    if wall <= 0:
        return 0.0
    stages = snapshot.get("stages") or []
    if not stages:
        return 0.0
    sink = stages[-1]
    vol = sink.get("bytes") or sink.get("items") or 0
    return vol / wall


def tier_signature(snapshot: Dict[str, Any]) -> tuple:
    """(stage, replay_tier) pairs for every tier-stamped stage —
    empty for pipelines without replaying stages, so the regime gate
    never fires for them."""
    return tuple(
        (s.get("name"), (s.get("extra") or {}).get("replay_tier"))
        for s in snapshot.get("stages") or []
        if (s.get("extra") or {}).get("replay_tier"))


class ExplorationRail:
    """Safe-exploration rails: one pending trial at a time, judged by
    the NEXT observation's throughput against the best accepted
    reference; revert + per-key cooldown on regression; optional
    per-group revert budgets (a group that keeps regressing is
    disabled); reference reset + trial discard on a regime change
    (replay-tier flip).

    Extracted from the Autotuner's revert/cooldown machinery so the
    verdict-driven controller (:mod:`dmlc_tpu.obs.control`) explores
    under the SAME guarantees. ``source`` keys the throughput
    reference (a controller watching two pipelines must not judge one
    by the other's rates); single-pipeline users leave it None.
    """

    def __init__(self, revert_tolerance: float = 0.9, cooldown: int = 3,
                 revert_budget: Optional[int] = None):
        check(0.0 < revert_tolerance <= 1.0,
              f"revert_tolerance must be in (0, 1], got {revert_tolerance}")
        check(cooldown >= 0, f"cooldown must be >= 0, got {cooldown}")
        self.revert_tolerance = revert_tolerance
        self.cooldown = cooldown
        self.revert_budget = revert_budget
        # epochs are PER SOURCE: with K pipelines observing one shared
        # rail, a global tick would expire every cooldown/freeze K×
        # faster than configured (each wall-epoch advances K times)
        self._epochs: Dict[Any, int] = {}
        self._best: Dict[Any, float] = {}      # source -> best accepted tp
        # key -> (source whose clock gates it, expiry epoch)
        self._frozen_until: Dict[str, tuple] = {}
        self._pending: Optional[Dict[str, Any]] = None
        # (group, source) -> revert count: budget charges ride the
        # charging source's lifetime (a dead pipeline's reverts must
        # not exhaust a family for every future pipeline)
        self._reverts: Dict[tuple, int] = {}
        self._regime: Dict[Any, tuple] = {}      # source -> last signature

    # -- state reads

    @property
    def epoch(self) -> int:
        return self._epochs.get(None, 0)

    def epoch_of(self, source: Any = None) -> int:
        return self._epochs.get(source, 0)

    @property
    def pending(self) -> Optional[Dict[str, Any]]:
        return self._pending

    def frozen(self, key: str) -> bool:
        gate = self._frozen_until.get(key)
        if gate is None:
            return False
        src, expiry = gate
        return self._epochs.get(src, 0) < expiry

    def exhausted(self, group: Optional[str],
                  source: Any = None) -> bool:
        """True when the group spent its revert budget for this source
        — its trials keep regressing, stop exploring it."""
        if group is None or self.revert_budget is None:
            return False
        return self._reverts.get((group, source), 0) >= \
            self.revert_budget

    def reverts(self, group: str, source: Any = None) -> int:
        return self._reverts.get((group, source), 0)

    def reverts_total(self, group: str) -> int:
        """Revert charges for the group summed across sources (the
        /control families view)."""
        return sum(v for (g, _), v in self._reverts.items()
                   if g == group)

    def best(self, source: Any = None) -> Optional[float]:
        return self._best.get(source)

    # -- trial lifecycle

    def begin(self, key: str, old: int, new: int,
              restore: Callable[[int], None], group: Optional[str] = None,
              source: Any = None, meta: Optional[Dict] = None) -> Dict:
        """Arm one trial (the caller already applied the new value).
        ``restore`` is called with ``old`` on revert/discard."""
        check(self._pending is None,
              "one trial at a time: resolve the pending trial first")
        self._pending = {"key": key, "group": group, "old": old,
                         "new": new, "restore": restore,
                         "source": source,
                         "epoch": self._epochs.get(source, 0),
                         "meta": meta or {}}
        return self._pending

    def note_regime(self, signature: tuple,
                    source: Any = None) -> Optional[Dict[str, Any]]:
        """Feed the epoch's regime signature (replay tiers). On a
        change: the throughput reference resets and any pending trial
        is DISCARDED (value restored, no freeze, no budget charge —
        the regime moved, not the knob). Returns the discarded trial
        or None."""
        prev = self._regime.get(source)
        self._regime[source] = signature
        if prev is None or signature == prev:
            return None
        self._best.pop(source, None)
        trial, self._pending = self._pending, None
        if trial is not None and trial["source"] == source:
            trial["restore"](trial["old"])
            trial["outcome"] = "discarded (replay tier changed)"
            return trial
        if trial is not None:
            self._pending = trial  # different source: keep it pending
        return None

    def observe(self, tp: float,
                source: Any = None) -> Optional[Dict[str, Any]]:
        """Feed one completed epoch's throughput. Resolves the pending
        trial for this source (accept, or revert + freeze + budget
        charge) and maintains the best-throughput reference. Returns
        the resolved trial dict (with ``outcome``/``throughput``) or
        None when no trial was pending."""
        trial = self._pending
        if trial is None or trial["source"] != source:
            best = self._best.get(source)
            if best is None or tp > best:
                self._best[source] = tp
            return None
        self._pending = None
        best = self._best.get(source)
        if best is not None and tp < self.revert_tolerance * best:
            trial["restore"](trial["old"])
            self.freeze(trial["key"], source=source)
            if trial["group"] is not None:
                k = (trial["group"], trial["source"])
                self._reverts[k] = self._reverts.get(k, 0) + 1
            trial["outcome"] = "reverted"
        else:
            trial["outcome"] = "accepted"
            if best is None or tp > best:
                self._best[source] = tp
        trial["throughput"] = round(tp, 2)
        return trial

    def cancel(self, key: str) -> Optional[Dict[str, Any]]:
        """Drop the pending trial for ``key`` without restore, freeze,
        or budget charge — the knob's owner is gone, there is nothing
        left to judge or restore. Returns the cancelled trial."""
        if self._pending is not None and self._pending["key"] == key:
            trial, self._pending = self._pending, None
            return trial
        return None

    def discard(self, source: Any = None) -> Optional[Dict[str, Any]]:
        """Discard this source's pending trial: value RESTORED, no
        freeze, no budget charge — the epoch that would have judged it
        measured something else (a drained credit bucket, a regime
        flip). Returns the discarded trial or None."""
        if self._pending is not None and self._pending["source"] == source:
            trial, self._pending = self._pending, None
            trial["restore"](trial["old"])
            trial["outcome"] = "discarded"
            return trial
        return None

    def drop_source(self, source: Any) -> None:
        """Forget a source entirely (its pipeline is gone): throughput
        reference, regime signature, revert charges, and any pending
        trial — a NEW pipeline that lands on a recycled source key
        must never be judged against a dead one's best, nor inherit a
        family exhausted by a ghost's reverts. The pending trial IS
        restored: a process-global knob trialed on the dead source's
        behalf (dead-owner knobs go through :meth:`cancel` first)
        would otherwise be left at its unjudged trial value forever."""
        self._best.pop(source, None)
        self._regime.pop(source, None)
        self._epochs.pop(source, None)
        for key in [k for k in self._reverts if k[1] == source]:
            del self._reverts[key]
        # freezes gated by the dead source's clock would never thaw
        # (its clock stops): release them
        for key in [k for k, (src, _) in self._frozen_until.items()
                    if src == source]:
            del self._frozen_until[key]
        if self._pending is not None and self._pending["source"] == source:
            trial, self._pending = self._pending, None
            trial["restore"](trial["old"])

    def freeze(self, key: str, epochs: Optional[int] = None,
               source: Any = None) -> None:
        """Freeze a knob for ``epochs`` (default cooldown) ticks of
        ``source``'s clock — the clock of whoever observed the
        condition, so another source's faster cadence cannot thaw it
        early."""
        self._frozen_until[key] = (source, self._epochs.get(source, 0)
                                   + (self.cooldown if epochs is None
                                      else epochs))

    def freeze_all(self, keys, epochs: Optional[int] = None,
                   source: Any = None) -> None:
        """The climate freeze: stop every knob for ``epochs`` (default
        cooldown) — a credit-limited verdict means wall rates reflect
        the scheduler, and chasing them would thrash."""
        for key in keys:
            self.freeze(key, epochs, source=source)

    def advance(self, source: Any = None) -> None:
        self._epochs[source] = self._epochs.get(source, 0) + 1


class Knob:
    """One tunable integer depth bound to a live pipeline object."""

    __slots__ = ("name", "stage", "get", "set", "lo", "hi", "initial")

    def __init__(self, name: str, stage: str, get: Callable[[], int],
                 set: Callable[[int], None], lo: int, hi: int):
        check(lo >= 1 and hi >= lo, f"knob {name}: bad bounds [{lo},{hi}]")
        self.name = name
        self.stage = stage  # probe name whose telemetry drives this knob
        self.get = get
        self.set = set
        self.lo = lo
        self.hi = hi
        self.initial = get()


class Autotuner:
    """One-trial-per-epoch hill climber over pipeline depth knobs,
    riding :class:`ExplorationRail` for accept/revert/cooldown."""

    def __init__(self, knobs: List[Knob], *,
                 grow_occupancy: float = 0.7,
                 shrink_occupancy: float = 0.15,
                 wait_frac_floor: float = 0.05,
                 revert_tolerance: float = 0.9,
                 cooldown: int = 3):
        self.knobs = list(knobs)
        self.grow_occupancy = grow_occupancy
        self.shrink_occupancy = shrink_occupancy
        self.wait_frac_floor = wait_frac_floor
        self.rail = ExplorationRail(revert_tolerance=revert_tolerance,
                                    cooldown=cooldown)
        self._log: List[Dict[str, Any]] = []

    # -- helpers

    @staticmethod
    def _stage(snapshot: Dict[str, Any], name: str) -> Optional[Dict]:
        for s in snapshot.get("stages", []):
            if s.get("name") == name:
                return s
        return None

    def _record(self, trial: Dict[str, Any]) -> None:
        self._log.append({k: trial[k] for k in
                          ("name", "epoch", "old", "new", "reason",
                           "outcome", "throughput") if k in trial})

    def _propose(self, snapshot: Dict[str, Any]) -> None:
        for knob in self.knobs:
            if self.rail.frozen(knob.name):
                continue
            stage = self._stage(snapshot, knob.stage)
            if stage is None:
                continue
            cur = knob.get()
            new = None
            reason = None
            occ = stage.get("queue_occupancy")
            if occ is not None:
                if occ >= self.grow_occupancy and cur < knob.hi:
                    new = min(cur * 2, knob.hi)
                    reason = f"occupancy {occ:.2f} ≥ {self.grow_occupancy}"
                elif (occ <= self.shrink_occupancy and cur > knob.lo
                      and (stage.get("wait_frac") or 0.0)
                      <= self.wait_frac_floor):
                    new = max(cur // 2, knob.lo)
                    reason = (f"occupancy {occ:.2f} ≤ "
                              f"{self.shrink_occupancy}, idle consumer")
            else:
                # windowed stage (to_device): grow while its drain wait
                # dominates the epoch
                extra = stage.get("extra") or {}
                xfer = extra.get("xfer_wait_s")
                wall = snapshot.get("wall_s") or 0.0
                if (xfer is not None and wall > 0
                        and xfer / wall > self.wait_frac_floor
                        and cur < knob.hi):
                    new = min(cur * 2, knob.hi)
                    reason = f"xfer wait {xfer / wall:.2f} of epoch"
            if new is not None and new != cur:
                knob.set(new)
                self.rail.begin(knob.name, cur, new, knob.set,
                                meta={"name": knob.name,
                                      "reason": reason})
                return  # one trial per epoch

    # -- public API

    def after_epoch(self, snapshot: Dict[str, Any]) -> None:
        """Feed one completed epoch's stats; may adjust one knob."""
        tp = epoch_throughput(snapshot)
        discarded = self.rail.note_regime(tier_signature(snapshot))
        if discarded is not None:
            # the serving tier flipped under this epoch: throughput is
            # a different regime (page replay vs parse differ ~5×) —
            # the rail restored the knob and reset the reference; the
            # discarded trial still proposes fresh from THIS epoch
            # (its stats describe the new regime honestly)
            discarded.update(name=discarded["key"],
                             epoch=discarded["epoch"],
                             reason=discarded["meta"].get("reason"),
                             throughput=round(tp, 2))
            self._record(discarded)
        resolved = self.rail.observe(tp)
        if resolved is not None:
            resolved.update(name=resolved["key"],
                            epoch=resolved["epoch"],
                            reason=resolved["meta"].get("reason"))
            self._record(resolved)
        if resolved is None or resolved["outcome"] != "reverted":
            self._propose(snapshot)
        # else: the reverted epoch ran under the BAD knob value — its
        # occupancies/waits must not seed the next trial (the latent
        # double-count); the next clean epoch proposes instead
        self.rail.advance()

    def values(self) -> Dict[str, int]:
        return {k.name: k.get() for k in self.knobs}

    def tuned(self) -> Dict[str, int]:
        """Knobs whose current value differs from their initial one —
        the 'set by the autotuner rather than a constant' evidence."""
        return {k.name: k.get() for k in self.knobs
                if k.get() != k.initial}

    def converged(self, last_n: int = 3) -> bool:
        """No accepted change in the last ``last_n`` decisions (or no
        decisions at all and no trial pending)."""
        if self.rail.pending is not None:
            return False
        recent = self._log[-last_n:]
        return all(d["outcome"] != "accepted" for d in recent) \
            if recent else self.rail.epoch >= last_n

    def report(self) -> Dict[str, Any]:
        best = self.rail.best()
        return {
            "epochs": self.rail.epoch,
            "values": self.values(),
            "initial": {k.name: k.initial for k in self.knobs},
            "tuned": self.tuned(),
            "decisions": list(self._log),
            "best_throughput": (round(best, 2)
                                if best is not None else None),
        }
