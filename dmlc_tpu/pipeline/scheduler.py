"""Process-wide multi-tenant pipeline scheduler: many pipelines, one
process, shared budgets.

Every ``CompiledPipeline`` before this module owned its own constants —
reader threads, chunk-prefetch queues, prefetch depths — sized as if it
were alone on the machine. N concurrent pipelines on a small box then
oversubscribe each other into mutual starvation: every tenant's queues
grow, every tenant's p99 dies, and nobody can say who ate the budget.
The :class:`PipelineScheduler` makes the budgets PROCESS-wide and
time-slices them across registered *tenants*:

- **Pull credits, deficit-round-robin.** Every batch a tenant's
  pipeline delivers costs one credit. Each tenant holds a deficit
  counter replenished by ``quantum × weight`` per *round*; a round
  advances when no active tenant can pay, and at latest every
  ``round_period_s`` — so a lone tenant runs effectively unthrottled
  (work conservation), competing saturators interleave in weight
  proportion, and every tenant keeps a guaranteed FLOOR of
  ``quantum × weight`` credits per round period no matter how a peer
  dribbles its hoard. An idle tenant retains up to its burst
  allowance (``burst × quantum × weight``), so a provisioned
  latency-sensitive tenant's whole sparse burst clears without ever
  going broke mid-burst (the p99 story); a saturating tenant is
  throttled the moment a peer demands its share.
- **Backpressure, not buffering.** A credit-blocked tenant stops
  pulling; its bounded queues fill; its producer threads block; its
  readers go idle — the throttle propagates UP the pipeline instead of
  letting a hot tenant's queues eat the shared arena pool. The
  scheduler also owns the queue-capacity knobs of every admitted
  pipeline (``parse.chunk_prefetch`` / ``prefetch.depth`` /
  ``shard.prefetch``): ``queue_budget`` items are divided across
  tenants by weight and across each tenant's pipelines evenly, so
  admission of a new tenant SHRINKS everyone's slack instead of
  growing the process footprint.
- **Admission control.** ``register_tenant(max_pipelines=...)`` caps
  each tenant's live pipelines; past the cap :meth:`admit` rejects
  (:class:`AdmissionError`) or queues (``admission="queue"``) until a
  slot frees. ``pause()``/``resume()`` administratively suspend a
  tenant (its pulls block, watchdog-visible).
- **Per-tenant accounting.** Counters/histograms land in the metrics
  registry under ``tenant.<name>.*`` (pulls, rows, bytes, credit
  waits, a batch-latency histogram whose p50/p99 render in
  ``/metrics``), epoch snapshots are stamped with a ``tenant`` label
  so :mod:`dmlc_tpu.obs.analyze` emits per-tenant bound verdicts, and
  ``GET /tenants`` (:mod:`dmlc_tpu.obs.serve`) renders one row per
  tenant: budget, credits, queue share, p99, watermark, last verdict.
  A credit-blocked pull registers with the stall watchdog as
  ``tenant/<name>.credits`` — a stall report NAMES the starved tenant.

Wiring mirrors the obs planes: :func:`install` /
:func:`install_if_env` under ``DMLC_TPU_SCHED``
(``launch_local(scheduler=...)`` exports it), one scheduler per
process, ``Pipeline.build(tenant="...")`` admits the compiled
pipeline and routes every delivered batch through
:meth:`PipelineScheduler.acquire`.
"""

from __future__ import annotations

import os
import re
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

from dmlc_tpu.obs import watchdog as _watchdog
from dmlc_tpu.obs.metrics import REGISTRY as _METRICS
from dmlc_tpu.utils.logging import DMLCError, check

__all__ = ["PipelineScheduler", "AdmissionError", "active", "install",
           "uninstall", "install_if_env", "ENV_SCHED", "MANAGED_KNOBS",
           "TENANTS_SCHEMA"]

# env contract (parallel.launch.launch_local(scheduler=...) sets it):
# "1" installs defaults; "quantum=4,queue=48,burst=2" overrides, plus
# per-tenant SLO declarations as "slo.<tenant>=<target>[:<window>
# [:<budget>]]" (e.g. "quantum=2,slo.victim=0.15:300:0.01")
ENV_SCHED = "DMLC_TPU_SCHED"

# bump when to_dict()'s top-level shape changes incompatibly
TENANTS_SCHEMA = 1

# the queue-capacity knobs the scheduler owns for admitted pipelines
# (Pipeline.build(tenant=...) withholds these from the autotuner —
# one owner per knob, the controller-adoption rule)
MANAGED_KNOBS = ("parse.chunk_prefetch", "prefetch.depth",
                 "shard.prefetch")


class AdmissionError(DMLCError):
    """A tenant is past its pipeline budget (or the queue timed out)."""


class _Tenant:
    """Internal per-tenant ledger (scheduler-lock protected)."""

    __slots__ = ("name", "weight", "max_pipelines", "admission",
                 "deficit", "demand", "last_demand", "paused", "pulls",
                 "rows", "bytes", "credit_waits", "credit_wait_s",
                 "admitted", "rejected", "queued", "queue_share",
                 "last_snapshot", "last_verdict", "slo")

    def __init__(self, name: str, weight: float, max_pipelines: int,
                 admission: str):
        self.name = name
        # the tenant's declared latency objective spec (None until
        # register_tenant(slo=...) declares one)
        self.slo: Optional[Dict[str, Any]] = None
        self.weight = weight
        self.max_pipelines = max_pipelines
        self.admission = admission
        self.deficit = 0.0
        self.demand = 0          # threads currently inside acquire()
        self.last_demand = 0.0   # monotonic stamp of the last acquire
        self.paused = False
        self.pulls = 0
        self.rows = 0
        self.bytes = 0
        self.credit_waits = 0
        self.credit_wait_s = 0.0
        self.admitted = 0
        self.rejected = 0
        self.queued = 0
        self.queue_share = None
        self.last_snapshot: Optional[Dict[str, Any]] = None
        self.last_verdict: Optional[Dict[str, Any]] = None


class PipelineScheduler:
    """Deficit-round-robin fair queueing over pull credits + shared
    queue budgets + per-tenant admission (see the module docstring)."""

    def __init__(self, *, quantum: float = 4.0, burst: float = 2.0,
                 queue_budget: int = 48,
                 active_horizon_s: float = 0.25,
                 round_period_s: float = 0.1, registry=None):
        check(quantum > 0, "scheduler: quantum must be > 0")
        check(burst >= 1.0, "scheduler: burst must be >= 1")
        check(queue_budget >= 1, "scheduler: queue_budget must be >= 1")
        check(active_horizon_s > 0,
              "scheduler: active_horizon_s must be > 0")
        self.quantum = float(quantum)
        self.burst = float(burst)
        self.queue_budget = int(queue_budget)
        # a tenant stays on the DRR active list for this long after
        # its last pull: between two pulls a tenant is OUTSIDE
        # acquire() (it is parsing the batch it just paid for), and a
        # round that advanced the moment nobody was mid-call would
        # hand a saturator unlimited credit the instant its peers
        # touched their own work. The horizon is also the bound on
        # how long a vanished tenant can hold the round back.
        self.active_horizon_s = float(active_horizon_s)
        # rounds also advance on a clock: a tenant holding unspent
        # credits but pulling slowly (a wire tenant mid-hydration, a
        # bursty interactive tenant trickling its hoard) must not
        # stall broke peers indefinitely — at latest every
        # round_period_s everyone active is replenished, so each
        # tenant's guaranteed FLOOR is quantum x weight credits per
        # round period (a rate), bursts ride the deficit cap, and
        # back-to-back rounds stay work-conserving when every
        # demander is broke.
        check(round_period_s > 0,
              "scheduler: round_period_s must be > 0")
        self.round_period_s = float(round_period_s)
        self._last_round = time.monotonic()
        self._registry = registry if registry is not None else _METRICS
        self._cond = threading.Condition()
        self._tenants: Dict[str, _Tenant] = {}
        # id(pipe) -> (weakref(pipe), tenant name): weak so a pipeline
        # that forgets close() still frees its admission slot
        self._pipes: Dict[int, tuple] = {}
        self.rounds = 0
        self._closed = False
        # one compact numeric collector: per-tenant occupancy of the
        # shared plane next to queue/engine stats in one snapshot
        self._metrics_key = self._registry.register(
            "scheduler", self, PipelineScheduler._collect)

    # ------------------------------------------------------ tenants

    def register_tenant(self, name: str, *, weight: float = 1.0,
                        max_pipelines: int = 4,
                        admission: str = "reject",
                        slo: Any = None) -> str:
        """Create (or re-weight) a tenant. ``admission`` is the
        over-budget policy for :meth:`admit`: "reject" raises
        :class:`AdmissionError`, "queue" blocks until a slot frees.

        ``slo`` declares the tenant's batch-latency objective — a
        float target in seconds, or a dict with ``target_s`` (or
        ``target``) plus optional ``window_s``/``budget`` — judged
        live by :mod:`dmlc_tpu.obs.slo` over the tenant's existing
        ``tenant.<name>.batch_s`` histogram (ROADMAP item 2's
        "declare a target instead of hand-tuning a weight"; this PR
        ships the judgment, a later one moves knobs on it). Declaring
        also gives the histogram SLO-aware bucket bounds, so
        attainment at the target is judged exactly — declare BEFORE
        the tenant's first batch."""
        check(weight > 0, f"tenant {name!r}: weight must be > 0")
        check(max_pipelines >= 1,
              f"tenant {name!r}: max_pipelines must be >= 1")
        check(admission in ("reject", "queue"),
              f"tenant {name!r}: admission must be 'reject' or 'queue'")
        spec = self._slo_spec(name, slo) if slo is not None else None
        with self._cond:
            t = self._tenants.get(name)
            if t is None:
                t = self._tenants[name] = _Tenant(
                    name, weight, max_pipelines, admission)
            else:
                t.weight = weight
                t.max_pipelines = max_pipelines
                t.admission = admission
            if spec is not None:
                t.slo = spec
            self._rebalance_locked()
        if spec is not None:
            self._declare_slo(name, spec)
        return name

    # ISSUE-19 naming: tenants DECLARE objectives at admission time
    add_tenant = register_tenant

    @staticmethod
    def _slo_spec(name: str, slo: Any) -> Dict[str, Any]:
        """Normalize the ``slo=`` shorthand (float target, or a dict
        with target/window/budget) into the obs.slo register() spec."""
        if isinstance(slo, (int, float)):
            slo = {"target_s": float(slo)}
        check(isinstance(slo, dict),
              f"tenant {name!r}: slo must be a target (seconds) or a "
              f"dict, got {type(slo).__name__}")
        spec: Dict[str, Any] = {}
        target = slo.get("target_s", slo.get("target"))
        check(target is not None and float(target) > 0,
              f"tenant {name!r}: slo needs a positive 'target_s'")
        spec["target_s"] = float(target)
        if slo.get("window_s") is not None:
            spec["window_s"] = float(slo["window_s"])
        if slo.get("budget") is not None:
            spec["budget"] = float(slo["budget"])
        unknown = set(slo) - {"target_s", "target", "window_s",
                              "budget"}
        check(not unknown,
              f"tenant {name!r}: unknown slo keys {sorted(unknown)}")
        return spec

    def _declare_slo(self, name: str, spec: Dict[str, Any]) -> None:
        """Register the tenant's objective with the SLO engine. Order
        matters: the SLO-aware bounded histogram is created FIRST so
        the engine's baseline sample sees the bucketing the judgment
        will use (bounds apply only at creation — an already-observed
        histogram keeps its buckets, and the judgment error is then
        bounded by one log2 bucket width instead of zero)."""
        from dmlc_tpu.obs import slo as _slo
        self._registry.histogram(f"tenant.{name}.batch_s",
                                 bounds=_slo.latency_bounds(
                                     spec["target_s"]))
        eng = _slo.active()
        if eng is None:
            eng = _slo.install(registry=self._registry)
        objective = re.sub(r"[^a-z0-9_.\-]", "_",
                           f"tenant.{name}".lower())
        eng.register(objective, metric=f"tenant.{name}.batch_s",
                     tenant=name, **spec)

    def tenants(self) -> List[str]:
        with self._cond:
            return sorted(self._tenants)

    def _tenant(self, name: str) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            raise DMLCError(
                f"scheduler: unknown tenant {name!r} (register_tenant "
                "first; registered: " + ", ".join(sorted(self._tenants))
                + ")")
        return t

    def pause(self, name: str) -> None:
        """Administratively suspend a tenant: its pulls block (the
        wait is watchdog-registered as ``tenant/<name>.paused``)."""
        with self._cond:
            self._tenant(name).paused = True
            self._cond.notify_all()

    def resume(self, name: str) -> None:
        with self._cond:
            self._tenant(name).paused = False
            self._cond.notify_all()

    # ---------------------------------------------------- admission

    def _live_pipes_locked(self, name: str) -> int:
        n = 0
        for pid, (ref, tname) in list(self._pipes.items()):
            if ref() is None:
                del self._pipes[pid]       # GC'ed without close()
            elif tname == name:
                n += 1
        return n

    def admit(self, tenant: str, pipe: Any,
              timeout_s: Optional[float] = 30.0) -> None:
        """Admit one compiled pipeline under ``tenant``'s budget.
        Past ``max_pipelines``: reject (:class:`AdmissionError`) or —
        ``admission="queue"`` — block until a slot frees (bounded by
        ``timeout_s``)."""
        with self._cond:
            t = self._tenant(tenant)
            deadline = (None if timeout_s is None
                        else time.monotonic() + timeout_s)
            queued = False
            while self._live_pipes_locked(tenant) >= t.max_pipelines:
                if t.admission != "queue":
                    t.rejected += 1
                    self._count(tenant, "rejected")
                    raise AdmissionError(
                        f"tenant {tenant!r} is at its pipeline budget "
                        f"({t.max_pipelines}); close one or raise "
                        "max_pipelines")
                if not queued:
                    queued = True
                    t.queued += 1
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    t.rejected += 1
                    self._count(tenant, "rejected")
                    raise AdmissionError(
                        f"tenant {tenant!r}: admission queue timed out "
                        f"after {timeout_s}s at budget "
                        f"{t.max_pipelines}")
                # the detail fn runs on the WATCHDOG thread without
                # this lock: it must only read (the mutating
                # _live_pipes_locked would race its dead-ref deletes
                # against lock-holding callers)
                token = _watchdog.begin_wait(
                    f"tenant/{tenant}.admission",
                    lambda: {"tenant": tenant,
                             "live": sum(
                                 1 for ref, tn in
                                 list(self._pipes.values())
                                 if tn == tenant
                                 and ref() is not None),
                             "budget": t.max_pipelines})
                try:
                    self._cond.wait(
                        timeout=min(0.25, remaining)
                        if remaining is not None else 0.25)
                finally:
                    _watchdog.end_wait(token)
            self._pipes[id(pipe)] = (weakref.ref(pipe), tenant)
            t.admitted += 1
            self._count(tenant, "admitted")
            self._rebalance_locked()

    def release(self, pipe: Any) -> None:
        """Free a pipeline's admission slot (CompiledPipeline.close)."""
        with self._cond:
            if self._pipes.pop(id(pipe), None) is not None:
                self._rebalance_locked()
                self._cond.notify_all()

    def _rebalance_locked(self) -> None:
        """Divide ``queue_budget`` across tenants (by weight) and each
        tenant's live pipelines (evenly), applying the shares through
        the pipelines' queue-capacity knobs. Runs on every admission-
        set change — a new tenant SHRINKS everyone's slack; the
        process's queued-item footprint stays bounded by the budget."""
        by_tenant: Dict[str, List[Any]] = {}
        for pid, (ref, tname) in list(self._pipes.items()):
            p = ref()
            if p is None:
                del self._pipes[pid]
                continue
            by_tenant.setdefault(tname, []).append(p)
        total_w = sum(self._tenants[n].weight for n in by_tenant)
        for name, pipes in by_tenant.items():
            t = self._tenants[name]
            share = max(1, int(self.queue_budget * t.weight
                               / max(total_w, 1e-9)))
            t.queue_share = share
            per_pipe = max(1, share // len(pipes))
            for p in pipes:
                for k in p.knobs():
                    if k.name in MANAGED_KNOBS:
                        k.set(max(k.lo, min(per_pipe, k.hi)))
        for name, t in self._tenants.items():
            if name not in by_tenant:
                t.queue_share = None

    # ------------------------------------------------- pull credits

    def acquire(self, tenant: str, cost: float = 1.0) -> None:
        """Charge one pull to the tenant, blocking under the DRR
        discipline when its deficit is spent and a competing tenant
        can still pay. The block registers with the stall watchdog as
        ``tenant/<name>.credits`` — a wedged tenant is NAMED in the
        stall report, not inferred."""
        t0: Optional[float] = None
        with self._cond:
            t = self._tenant(tenant)
            # liveness: a cost past one burst allowance could never be
            # saved up (round replenishment caps at the burst)
            cost = min(float(cost), self.burst * self.quantum * t.weight)
            t.demand += 1
            t.last_demand = time.monotonic()
            try:
                while True:
                    if self._closed:
                        return
                    if t.paused:
                        t0 = t0 or time.perf_counter()
                        token = _watchdog.begin_wait(
                            f"tenant/{tenant}.paused",
                            lambda: {"tenant": tenant, "paused": True})
                        try:
                            self._cond.wait(timeout=0.25)
                        finally:
                            _watchdog.end_wait(token)
                        continue
                    if t.deficit >= cost:
                        t.deficit -= cost
                        self._cond.notify_all()
                        break
                    # broke: advance the round only when NO other
                    # ACTIVE, unpaused tenant can still pay — else
                    # wait for them to spend their slice (fair
                    # queueing). "Active" spans the horizon, not just
                    # the instants a peer is inside acquire().
                    now = time.monotonic()
                    payable = any(
                        o is not t and not o.paused
                        and self._active_locked(o, now)
                        and o.deficit >= 1.0
                        for o in self._tenants.values())
                    if (not payable or now - self._last_round
                            >= self.round_period_s):
                        self._advance_round_locked()
                        continue
                    t0 = t0 or time.perf_counter()
                    t.credit_waits += 1
                    token = _watchdog.begin_wait(
                        f"tenant/{tenant}.credits",
                        lambda: {"tenant": tenant,
                                 "deficit": round(t.deficit, 2),
                                 "round": self.rounds})
                    try:
                        self._cond.wait(timeout=min(
                            0.25, max(0.005, self.round_period_s
                                      - (now - self._last_round))))
                    finally:
                        _watchdog.end_wait(token)
            finally:
                t.demand -= 1
                if t.demand == 0:
                    # classic DRR: an emptied queue leaves the active
                    # list; what an idle tenant can hoard is capped at
                    # its BURST allowance — enough that a provisioned
                    # latency tenant's whole sparse burst clears
                    # without ever going broke mid-burst, bounded so a
                    # long sleep is not an unbounded credit bank
                    t.deficit = min(t.deficit, self.burst
                                    * self.quantum * t.weight)
                self._cond.notify_all()
        if t0 is not None:
            dt = time.perf_counter() - t0
            with self._cond:
                t.credit_wait_s += dt
            self._registry.histogram(
                f"tenant.{tenant}.credit_wait_s").observe(dt)

    def _active_locked(self, t: _Tenant, now: float) -> bool:
        return (t.demand > 0
                or now - t.last_demand < self.active_horizon_s)

    def _advance_round_locked(self) -> None:
        self.rounds += 1
        now = time.monotonic()
        self._last_round = now
        for t in self._tenants.values():
            if self._active_locked(t, now) and not t.paused:
                cap = self.burst * self.quantum * t.weight
                t.deficit = min(t.deficit + self.quantum * t.weight,
                                cap)
        self._cond.notify_all()

    # ----------------------------------------------- accounting

    def _count(self, tenant: str, what: str, n: int = 1) -> None:
        self._registry.counter(f"tenant.{tenant}.{what}").inc(n)

    def note_batch(self, tenant: str, wait_s: float,
                   rows: int = 0, nbytes: int = 0) -> None:
        """One delivered batch: per-tenant volume + latency. The
        latency histogram's p50/p99 are the ``/tenants`` row numbers
        (and render as ``dmlc_tenant_<name>_batch_s_p99`` gauges)."""
        with self._cond:
            t = self._tenant(tenant)
            t.pulls += 1
            t.rows += int(rows)
            t.bytes += int(nbytes)
        self._count(tenant, "pulls")
        self._registry.histogram(
            f"tenant.{tenant}.batch_s").observe(wait_s)

    def note_epoch(self, tenant: str,
                   snapshot: Optional[Dict[str, Any]]) -> None:
        """One completed epoch: store the tenant-stamped snapshot and
        derive its bound verdict (obs.analyze) so ``/tenants`` rows
        carry a last-verdict column per tenant."""
        if snapshot is None:
            return
        verdict = None
        try:
            from dmlc_tpu.obs import analyze as _an
            verdict = _an.attribute(snapshot)
        except Exception:  # noqa: BLE001 — telemetry must not kill
            verdict = None
        with self._cond:
            t = self._tenant(tenant)
            t.last_snapshot = snapshot
            if verdict is not None:
                t.last_verdict = verdict

    # ----------------------------------------------- introspection

    def _tenant_row_locked(self, t: _Tenant) -> Dict[str, Any]:
        live = self._live_pipes_locked(t.name)
        row: Dict[str, Any] = {
            "weight": t.weight,
            "deficit": round(t.deficit, 2),
            "quantum": round(self.quantum * t.weight, 2),
            "paused": t.paused,
            "pipelines": live,
            "max_pipelines": t.max_pipelines,
            "admission": t.admission,
            "queue_share": t.queue_share,
            "pulls": t.pulls,
            "rows": t.rows,
            "bytes": t.bytes,
            "credit_waits": t.credit_waits,
            "credit_wait_s": round(t.credit_wait_s, 4),
            "admitted": t.admitted,
            "rejected": t.rejected,
            "queued": t.queued,
        }
        h = self._registry.histogram(f"tenant.{t.name}.batch_s")
        s = h.summary()
        row["batch_p50_s"] = s.get("p50")
        row["batch_p99_s"] = s.get("p99")
        row["batches"] = s.get("count")
        if t.slo is not None:
            # the declared objective (judged live on GET /slo)
            row["slo"] = dict(t.slo)
        # live queue occupancy + streaming watermark off the tenant's
        # admitted pipelines (weak reads; a dead ref just drops out)
        occ = []
        stream = None
        for pid, (ref, tname) in list(self._pipes.items()):
            p = ref()
            if p is None or tname != t.name:
                continue
            snap = getattr(p, "stats", lambda: None)()
            if snap:
                occ.extend(
                    st["queue_occupancy"]
                    for st in snap.get("stages") or []
                    if st.get("queue_occupancy") is not None)
            ss = getattr(p, "stream_stats", lambda: None)()
            if ss is not None:
                stream = ss
        row["queue_occupancy"] = (round(sum(occ) / len(occ), 3)
                                  if occ else None)
        if stream is not None:
            row["watermark"] = stream
        if t.last_verdict is not None:
            v = t.last_verdict
            row["last_verdict"] = {
                "verdict_id": v.get("verdict_id"),
                "bound": v.get("bound"),
                "band": v.get("band"),
                "confidence": v.get("confidence"),
            }
        return row

    def to_dict(self) -> Dict[str, Any]:
        """The ``/tenants`` payload: one row per tenant."""
        with self._cond:
            return {
                "schema": TENANTS_SCHEMA,
                "quantum": self.quantum,
                "burst": self.burst,
                "queue_budget": self.queue_budget,
                "rounds": self.rounds,
                "tenants": {name: self._tenant_row_locked(t)
                            for name, t in
                            sorted(self._tenants.items())},
            }

    def _collect(self) -> Dict[str, Any]:
        """Compact numeric collector shape for the metrics registry."""
        with self._cond:
            return {
                "rounds": self.rounds,
                "queue_budget": self.queue_budget,
                "tenants": {
                    name: {"deficit": round(t.deficit, 2),
                           "pipelines": self._live_pipes_locked(name),
                           "pulls": t.pulls,
                           "credit_waits": t.credit_waits,
                           "paused": t.paused}
                    for name, t in self._tenants.items()},
            }

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._metrics_key is not None:
            self._registry.unregister(self._metrics_key)
            self._metrics_key = None


# ------------------------------------------------- process wiring
# (the serve/flight/history/control install contract)

_active: Optional[PipelineScheduler] = None
_lock = threading.Lock()


def active() -> Optional[PipelineScheduler]:
    return _active


def install(scheduler: Optional[PipelineScheduler] = None,
            **opts: Any) -> PipelineScheduler:
    """Install the process scheduler (idempotent: a second call
    returns the running one, like obs.serve.serve)."""
    global _active
    with _lock:
        if _active is not None:
            return _active
        _active = (scheduler if scheduler is not None
                   else PipelineScheduler(**opts))
        return _active


def uninstall() -> None:
    global _active
    with _lock:
        sched, _active = _active, None
    if sched is not None:
        sched.close()


def install_if_env() -> Optional[PipelineScheduler]:
    """Gang-worker hook: install under ``DMLC_TPU_SCHED`` — "1"/"true"
    for defaults, or "quantum=4,queue=48,burst=2" overrides, plus
    ``slo.<tenant>=<target>[:<window>[:<budget>]]`` per-tenant SLO
    declarations — else no-op (launch_local(scheduler=...) sets the
    var per worker)."""
    raw = os.environ.get(ENV_SCHED, "").strip()
    if not raw or raw in ("0", "false"):
        return None
    opts: Dict[str, Any] = {}
    slos: Dict[str, Dict[str, Any]] = {}
    if raw not in ("1", "true"):
        try:
            for part in raw.split(","):
                k, _, v = part.partition("=")
                k = k.strip()
                if k == "quantum":
                    opts["quantum"] = float(v)
                elif k == "queue":
                    opts["queue_budget"] = int(v)
                elif k == "burst":
                    opts["burst"] = float(v)
                elif k.startswith("slo.") and k[len("slo."):]:
                    # slo.<tenant>=<target>[:<window>[:<budget>]]
                    fields = v.split(":")
                    if not 1 <= len(fields) <= 3:
                        raise ValueError(v)
                    spec: Dict[str, Any] = {
                        "target_s": float(fields[0])}
                    if len(fields) > 1:
                        spec["window_s"] = float(fields[1])
                    if len(fields) > 2:
                        spec["budget"] = float(fields[2])
                    slos[k[len("slo."):]] = spec
                else:
                    raise ValueError(k)
        except ValueError:
            from dmlc_tpu.obs.log import warn_once
            warn_once("sched-env-malformed",
                      f"scheduler: malformed {ENV_SCHED}={raw!r} "
                      "(want '1' or 'quantum=4,queue=48,burst=2"
                      ",slo.victim=0.15:300:0.01'); "
                      "installing defaults", all_ranks=True)
            opts = {}
            slos = {}
    sched = install(**opts)
    for tenant, spec in slos.items():
        try:
            sched.register_tenant(tenant, slo=spec)
        except DMLCError as e:
            from dmlc_tpu.obs.log import warn_once
            warn_once("sched-env-slo-rejected",
                      f"scheduler: {ENV_SCHED} slo.{tenant} rejected "
                      f"({e}); tenant registered without an objective",
                      all_ranks=True)
            sched.register_tenant(tenant)
    return sched
