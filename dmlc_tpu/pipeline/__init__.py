"""dmlc_tpu.pipeline — declarative dataset-pipeline graphs.

The composition layer over the IO/data/parallel machinery: a tf.data-
style chain (``Pipeline.from_uri(...).parse(...).prefetch().to_device()``)
that compiles down to InputSplit / Parser / ThreadedIter / DiskRowIter /
ShardedRowBlockIter, with a telemetry probe at every stage boundary
(``dmlc_tpu.pipeline.stats``) and a between-epoch autotuner over queue
depths (``dmlc_tpu.pipeline.autotune``). See docs/pipeline.md.
"""

from dmlc_tpu.pipeline.autotune import Autotuner, Knob
from dmlc_tpu.pipeline.graph import CompiledPipeline, Pipeline
from dmlc_tpu.pipeline.scheduler import AdmissionError, PipelineScheduler
from dmlc_tpu.pipeline.stages import StageSpec
from dmlc_tpu.pipeline.stats import (
    PIPELINE_STATS_SCHEMA, StageProbe, snapshot,
)

__all__ = [
    "Pipeline", "CompiledPipeline", "StageSpec",
    "Autotuner", "Knob",
    "PipelineScheduler", "AdmissionError",
    "StageProbe", "snapshot", "PIPELINE_STATS_SCHEMA",
]
