"""JAX/XLA ops over CSR batches — the TPU-native compute seam.

The reference has no device compute; on TPU the point of parse-to-HBM is
that downstream learners (XGBoost-style linear/boosted models) consume CSR
batches with XLA-compiled kernels. XLA wants static shapes, so batches are
padded to shape buckets (see dmlc_tpu.parallel.pad_to_bucket) and all ops
here are shape-polymorphic only in the Python sense — under jit each
bucket compiles once.

Representations:
- flat CSR: (offset[n+1], index[nnz], value[nnz]) — SpMV via segment-sum
  (row ids recovered with searchsorted; fully jittable, no dynamic shapes).
- padded ELL: (index[n, k], value[n, k]) with zero-padded tails — the
  MXU-friendly layout for dense-ish downstream math.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["spmv", "segment_spmv", "csr_to_dense", "csr_to_padded_rows",
           "sdot_rows", "csr_row_ids", "sharded_spmv", "segment_sum"]

# The ONE spelling of segment-sum used across the package (models/fm.py
# and every op here): jax.ops.segment_sum is the supported public API in
# the pinned JAX; if it ever moves, this is the single line to update.
segment_sum = jax.ops.segment_sum


def csr_row_ids(offset: jnp.ndarray, nnz: int) -> jnp.ndarray:
    """row id of every nonzero: row_ids[k] = i s.t. offset[i] <= k < offset[i+1].

    Padded tail entries (k >= offset[-1]) map to row n (one-past-last) so
    segment ops can drop them via num_segments=n.
    """
    return jnp.searchsorted(offset, jnp.arange(nnz, dtype=offset.dtype),
                            side="right") - 1


@partial(jax.jit, static_argnames=("num_rows",))
def segment_spmv(offset: jnp.ndarray, index: jnp.ndarray,
                 value: jnp.ndarray, weights: jnp.ndarray,
                 num_rows: int) -> jnp.ndarray:
    """y[i] = Σ_{k in row i} value[k] * weights[index[k]] (CSR · dense).

    Padded nonzeros must carry value 0 (pad_to_bucket guarantees it), so
    they contribute nothing regardless of their index.
    """
    row_ids = csr_row_ids(offset, index.shape[0])
    contrib = value * jnp.take(weights, index.astype(jnp.int32), axis=0)
    return segment_sum(contrib, row_ids.astype(jnp.int32),
                       num_segments=num_rows)


def spmv(offset, index, value, weights) -> jnp.ndarray:
    """Convenience wrapper: num_rows from offset shape."""
    return segment_spmv(jnp.asarray(offset), jnp.asarray(index),
                        jnp.asarray(value), jnp.asarray(weights),
                        num_rows=int(offset.shape[0]) - 1)


@partial(jax.jit, static_argnames=("num_rows", "num_cols"))
def csr_to_dense(offset: jnp.ndarray, index: jnp.ndarray,
                 value: jnp.ndarray, num_rows: int,
                 num_cols: int) -> jnp.ndarray:
    """Scatter CSR into a dense [num_rows, num_cols] float32 matrix."""
    row_ids = csr_row_ids(offset, index.shape[0]).astype(jnp.int32)
    dense = jnp.zeros((num_rows + 1, num_cols), jnp.float32)
    dense = dense.at[row_ids, index.astype(jnp.int32)].add(value)
    return dense[:num_rows]


def csr_to_padded_rows(offset: np.ndarray, index: np.ndarray,
                       value: Optional[np.ndarray],
                       max_nnz_per_row: Optional[int] = None
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side CSR → padded ELL (index[n,k], value[n,k], mask[n,k]).

    Pad index with 0 and value with 0.0 so downstream gather+MXU matmuls
    are mask-free for linear math.
    """
    offset = np.asarray(offset, np.int64)
    n = len(offset) - 1
    lens = np.diff(offset)
    k = int(max_nnz_per_row if max_nnz_per_row is not None
            else (lens.max() if n else 0))
    out_idx = np.zeros((n, k), np.int32)
    out_val = np.zeros((n, k), np.float32)
    mask = np.zeros((n, k), bool)
    vals = (np.asarray(value, np.float32) if value is not None
            else np.ones(len(index), np.float32))
    for i in range(n):
        m = min(int(lens[i]), k)
        lo = int(offset[i])
        out_idx[i, :m] = index[lo:lo + m]
        out_val[i, :m] = vals[lo:lo + m]
        mask[i, :m] = True
    return out_idx, out_val, mask


@jax.jit
def sdot_rows(padded_index: jnp.ndarray, padded_value: jnp.ndarray,
              weights: jnp.ndarray) -> jnp.ndarray:
    """Batched Row::SDot over padded ELL rows (reference: Row<I>::SDot)."""
    gathered = jnp.take(weights, padded_index.astype(jnp.int32), axis=0)
    return jnp.sum(gathered * padded_value, axis=-1)


def sharded_spmv(batch, weights, mesh, axis: str = "data"):
    """SpMV over a global sharded batch (dmlc_tpu.parallel layout):
    batch arrays are [num_devices, ...] sharded on ``axis``; each device
    computes its own CSR block with static shapes under shard_map;
    weights are replicated. Returns y [num_devices, row_bucket] sharded
    the same way — the canonical consumption pattern for downstream
    learners (per-device partial results, psum-able gradients).
    """
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:  # pre-0.4.35 jax: experimental namespace
        from jax.experimental.shard_map import shard_map

    row_bucket = batch["offset"].shape[1] - 1

    def block_fn(offset, index, value, w):
        # leading device dim is 1 inside the shard
        return segment_spmv(offset[0], index[0], value[0], w,
                            num_rows=row_bucket)[None]

    fn = shard_map(
        block_fn, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P()),
        out_specs=P(axis))
    return jax.jit(fn)(batch["offset"], batch["index"], batch["value"],
                       jnp.asarray(weights))
