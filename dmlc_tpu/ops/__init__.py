"""TPU compute ops over CSR RowBlocks (JAX/XLA; the device-side seam).

No reference counterpart — dmlc-core has no tensor ops; these are the
TPU-native consumers that make HBM-resident CSR batches useful
(SpMV/row-gather for the XGBoost/linear-learner style downstream).
"""

from dmlc_tpu.ops.csr import (
    csr_to_padded_rows, spmv, csr_to_dense, segment_spmv, sdot_rows,
    sharded_spmv, csr_row_ids,
)

__all__ = ["csr_to_padded_rows", "spmv", "csr_to_dense", "segment_spmv",
           "sdot_rows", "sharded_spmv", "csr_row_ids"]
