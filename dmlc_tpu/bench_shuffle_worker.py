"""Worker for bench_suite config 23 (global_shuffle).

Run under ``parallel.launch_local(serve_ports=True)`` as a REAL
2-process gang over one larger-than-window RecordIO corpus on shared
disk: each rank gets its OWN page-store root (simulating hosts that do
not share a cache), starts its StatusServer — whose ``/pages``
endpoint doubles as the shuffle window exchange — and drains its
round-robin half of the seeded global permutation:

- window ``w`` is owned by rank ``w % world``: the owner assembles it
  from the source byte ranges (wire), everyone else peer-fetches the
  committed window page from the owner's ``/pages`` — the
  ``shuffle.bytes.peer`` fraction is the config's acceptance;
- a second epoch must replay entirely from the local store on EVERY
  rank (window names are seed/epoch-invariant), wire and peer deltas
  flat;
- each rank reports its delivered records twice: in permutation order
  (per-record sha256, for the cross-world byte-identity merge) and the
  counter deltas. The supervisor round-robin-merges the two ordered
  streams and compares against an in-process world-1 drain — same
  seed ⇒ same global order at any world size.

No jax: ranks coordinate through file barriers in ``out_dir``.

Usage: bench_shuffle_worker.py <corpus> <out_dir> <seed> <window_bytes>
"""

import hashlib
import json
import os
import sys
import time


def _barrier(out_dir: str, phase: str, rank: int, world: int,
             timeout_s: float = 120.0) -> None:
    from dmlc_tpu.io.stream import create_stream
    with create_stream(os.path.join(out_dir, f"barrier-{phase}.{rank}"),
                       "w") as s:
        s.write(b"1")
    deadline = time.monotonic() + timeout_s
    want = [os.path.join(out_dir, f"barrier-{phase}.{r}")
            for r in range(world)]
    while not all(os.path.exists(p) for p in want):
        if time.monotonic() > deadline:
            raise TimeoutError(f"gang barrier {phase!r}: peers missing "
                               f"after {timeout_s}s")
        time.sleep(0.02)


_COUNTERS = ("shuffle.records.local", "shuffle.records.peer",
             "shuffle.records.wire", "shuffle.bytes.local",
             "shuffle.bytes.peer", "shuffle.bytes.wire",
             "shuffle.windows.built", "shuffle.windows.fetched")


def _counters() -> dict:
    from dmlc_tpu.obs.metrics import REGISTRY
    return {name: REGISTRY.counter(name).value for name in _COUNTERS}


def _delta(a: dict, b: dict) -> dict:
    return {k: b[k] - a[k] for k in a}


def main() -> int:
    corpus, out_dir = sys.argv[1], sys.argv[2]
    seed, window_bytes = int(sys.argv[3]), int(sys.argv[4])
    rank = int(os.environ["DMLC_TPU_TASK_ID"])
    world = int(os.environ["DMLC_TPU_NUM_WORKER"])

    # each rank its own store root — a shared one would exchange
    # windows through the filesystem and prove nothing about /pages
    from dmlc_tpu.io.pagestore import ENV_STORE_DIR
    os.environ[ENV_STORE_DIR] = os.path.join(out_dir, f"store-{rank}")

    from dmlc_tpu.obs.serve import serve_if_env
    from dmlc_tpu.resilience import RetryPolicy, set_policy
    from dmlc_tpu.shuffle import GlobalShuffleSplit

    # patience at the peer seam: a miss usually means the window's
    # owner is still assembling it — short waits keep the non-owner
    # off the wire (it still degrades to the source after the ladder)
    set_policy("io.objstore.peer",
               RetryPolicy(max_attempts=8, base_delay_s=0.05,
                           max_delay_s=0.4))
    srv = serve_if_env()
    if srv is None:
        raise RuntimeError("bench_shuffle_worker needs "
                           "launch_local(serve_ports=...)")

    sp = GlobalShuffleSplit(corpus, rank, world, "recordio", seed=seed,
                            window_bytes=window_bytes)

    def epoch() -> dict:
        before = _counters()
        hashes = []
        t0 = time.perf_counter()
        n_bytes = 0
        while True:
            rec = sp.next_record()
            if rec is None:
                break
            n_bytes += len(rec)
            hashes.append(hashlib.sha256(rec).hexdigest())
        wall = time.perf_counter() - t0
        return {"wall_s": wall, "bytes": n_bytes, "n": len(hashes),
                "hashes": hashes,
                "counters": _delta(before, _counters())}

    # both servers must be serving before any rank's cold epoch: the
    # peer fetch path IS the other rank's StatusServer
    _barrier(out_dir, "start", rank, world)
    cold = epoch()
    _barrier(out_dir, "cold", rank, world)
    sp.before_first()  # advances to epoch 1
    warm = epoch()
    warm.pop("hashes")  # the merge only needs the cold ordering

    from dmlc_tpu.io.stream import create_stream
    with create_stream(os.path.join(out_dir, f"shuffle-{rank}.json"),
                       "w") as s:
        s.write(json.dumps({"rank": rank, "world": world,
                            "windows": sp.reader.num_windows,
                            "cold": cold, "warm": warm}).encode())
    _barrier(out_dir, "done", rank, world)
    return 0


if __name__ == "__main__":
    sys.exit(main())
