"""Worker for bench_suite config 21 (ckpt_restore_fanout).

Two modes, two real gangs over one ``obj://`` checkpoint root:

- ``save`` — a THREE-writer gang under ``launch_local(
  rendezvous=True)``: each rank device-direct-saves its own disjoint
  leaves (``w<rank>/l<i>``) with ``save(step, tree, writer=rank,
  num_writers=3)``, mid-epoch (the rank has live rendezvous progress
  when the step lands, so the gang stamp rides in meta.json). A
  second save with ONE mutated leaf measures the incremental path:
  unchanged pages dedup by content digest and upload nothing.

- ``restore`` — a TWO-rank gang under ``launch_local(
  serve_ports=True)``, each rank a cold host (its OWN page-store
  root): ``prefetch()`` wire-fetches only the pages ``content_owner``
  assigns to this rank at world 2 (an elastic re-cut: the saving
  world was 3), a file barrier guarantees every page is staged at
  its owner, then a FULL ``restore(like=None)`` assembles every
  leaf — the other half arriving from the peer's ``/pages`` tier,
  not the wire. Each rank reports its wire/peer/local byte split
  plus a per-leaf digest so the suite can prove the different-world
  restore byte-identical.

Usage: bench_ckpt_worker.py <out_dir> <save|restore> <total_mb>
"""

import hashlib
import json
import os
import sys
import time

ROOT = "obj://bench/ckpt"
WRITERS = 3      # saving gang world
# leaves per writer (96 pages gang-wide): content_owner cuts pages by
# digest hash, so enough pages are needed for the per-rank byte split
# to concentrate near 1/N — a handful of big pages can skew 60/40
LEAVES = 32
STEP = 5         # first full save
STEP_INCR = 6    # the incremental re-save (one leaf mutated)


def _barrier(out_dir, phase, rank, world, timeout_s=180.0):
    from dmlc_tpu.io.stream import create_stream
    with create_stream(os.path.join(out_dir, f"barrier-{phase}.{rank}"),
                       "w") as s:
        s.write(b"1")
    deadline = time.monotonic() + timeout_s
    want = [os.path.join(out_dir, f"barrier-{phase}.{r}")
            for r in range(world)]
    while not all(os.path.exists(p) for p in want):
        if time.monotonic() > deadline:
            raise TimeoutError(f"gang barrier {phase!r}: peers missing "
                               f"after {timeout_s}s")
        time.sleep(0.02)


def _leaf(writer, i, elems):
    import numpy as np
    # seed stride > LEAVES: every leaf distinct gang-wide, else
    # content digests dedup across writers and shrink the page set
    rng = np.random.RandomState(1000 + writer * 100 + i)
    return rng.rand(elems).astype(np.float32)


def _tree(writer, elems):
    return {f"w{writer}": {f"l{i}": _leaf(writer, i, elems)
                           for i in range(LEAVES)}}


def _shas(host):
    return {k: hashlib.sha256(
        memoryview(v).tobytes()).hexdigest()[:16]
        for k, v in host.items()}


def _wire():
    from dmlc_tpu.obs.metrics import REGISTRY
    return REGISTRY.counter("objstore.bytes").value


def main() -> int:
    out_dir, mode, total_mb = sys.argv[1], sys.argv[2], int(sys.argv[3])
    rank = int(os.environ["DMLC_TPU_TASK_ID"])
    world = int(os.environ["DMLC_TPU_NUM_WORKER"])

    # own page-store root per rank — restore ranks are cold hosts, and
    # a shared store would serve pages through the filesystem and
    # falsify the wire split
    from dmlc_tpu.io.pagestore import ENV_STORE_DIR
    os.environ[ENV_STORE_DIR] = os.path.join(out_dir,
                                             f"store-{mode}-{rank}")

    from dmlc_tpu.io.checkpoint import ShardedCheckpoint
    from dmlc_tpu.io.stream import create_stream

    elems = (total_mb << 20) // (WRITERS * LEAVES * 4)
    ck = ShardedCheckpoint(ROOT)

    if mode == "save":
        from dmlc_tpu.rendezvous import install_if_env as rndv_if_env
        cli = rndv_if_env()
        if cli is None:
            raise RuntimeError("bench_ckpt_worker save mode needs "
                               "launch_local(rendezvous=True)")
        # mid-epoch: commit live progress BEFORE the step lands, so
        # the checkpoint's gang stamp describes a consuming gang
        v = cli.view()
        if v["epoch"] is not None:
            cli.commit(rank, 1, epoch=v["epoch"])
        tree = _tree(rank, elems)
        t0 = time.perf_counter()
        ck.save(STEP, tree, metadata={"epoch": 0, "batch": 1},
                writer=rank, num_writers=world)
        full_wall = time.perf_counter() - t0
        full_written = ck.last_save_bytes_written
        _barrier(out_dir, "full-save", rank, world)
        # the incremental re-save: rank 0 mutates ONE leaf of 96
        if rank == 0:
            tree["w0"]["l0"] = tree["w0"]["l0"] + 1.0
        t0 = time.perf_counter()
        ck.save(STEP_INCR, tree, metadata={"epoch": 0, "batch": 2},
                writer=rank, num_writers=world)
        incr_wall = time.perf_counter() - t0
        flat = {f"w{rank}/{k}": a for k, a in tree[f"w{rank}"].items()}
        out = {"rank": rank, "mode": mode,
               "full_written": full_written,
               "full_wall_s": full_wall,
               "incr_written": ck.last_save_bytes_written,
               "incr_reused": ck.last_save_bytes_reused,
               "incr_wall_s": incr_wall,
               "leaves": _shas(flat)}
        cli.leave()
    else:
        from dmlc_tpu.obs.serve import serve_if_env
        if serve_if_env() is None:
            raise RuntimeError("bench_ckpt_worker restore mode needs "
                               "launch_local(serve_ports=True)")
        wire0 = _wire()
        # all /pages servers up before anyone's prefetch
        _barrier(out_dir, "serve-up", rank, world)
        t0 = time.perf_counter()
        ck.prefetch()
        # every page staged at its content_owner before assembly: no
        # rank races ahead and pays wire for a peer's unfetched page
        _barrier(out_dir, "prefetched", rank, world)
        host, user = ck.restore(like=None)
        wall = time.perf_counter() - t0
        out = {"rank": rank, "mode": mode, "wall_s": wall,
               "step": ck.latest_step(), "user": user,
               "restored_bytes": ck.last_restore_bytes_read,
               "wire_bytes": _wire() - wire0,
               "split": {"local": ck.last_restore_local_bytes,
                         "peer": ck.last_restore_peer_bytes,
                         "wire": ck.last_restore_wire_bytes},
               "leaves": _shas(host)}
        # stay alive (serving) until every rank finished assembling
        _barrier(out_dir, "done", rank, world)
    with create_stream(os.path.join(out_dir,
                                    f"{mode}-{rank}.json"), "w") as s:
        s.write(json.dumps(out).encode())
    return 0


if __name__ == "__main__":
    sys.exit(main())
