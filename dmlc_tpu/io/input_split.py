"""Deterministic sharded input splitting with record-boundary realignment.

Reference: src/io/input_split_base.{h,cc} (InputSplitBase: prefix-sum file
sizes → per-part byte range → SeekRecordBegin realignment; Chunk reads),
src/io/line_split.{h,cc} (LineSplitter), src/io/recordio_split.{h,cc}
(RecordIOSplitter), src/io/indexed_recordio_split.{h,cc},
src/io/single_file_split.h, include/dmlc/io.h (InputSplit decl).

### The sharding contract (frozen; tested in tests/test_input_split.py)

Files are logically concatenated in listing order into a global byte space of
size ``total``. For ``num_parts`` parts, with
``nstep = ceil(total / num_parts)``, part ``k`` owns the raw byte range
``[min(nstep*k, total), min(nstep*(k+1), total))``, each endpoint aligned
down to ``align_bytes`` and then *realigned forward* to a record boundary by
the shared rule ``boundary(x)``:

- ``boundary(x) = x`` if x is 0, total, or a file boundary;
- otherwise scan forward from x **through** the next record terminator to
  the start of the following record (clipped at the containing file's end).

Because both a part's begin and its predecessor's end are computed by the
*same* ``boundary`` function, every record lands in exactly one part —
coverage and no-overlap hold for any (num_parts, chunk size, file layout).
This mirrors the reference, where SeekRecordBegin is applied to both
``offset_begin_`` and ``offset_end_``.

Record definitions:
- text: a record is a maximal run of bytes containing no '\\n'/'\\r'
  (empty lines yield no records; CRLF-safe). Terminator scan = skip to
  first newline byte, then past the newline run.
- recordio: a record is a RecordIO frame sequence (multi-frame records are
  kept whole); boundary scan = next 4-aligned magic whose frame cflag is
  whole(0) or start(1) — continuation frames are not record starts.
"""

from __future__ import annotations

import struct
from bisect import bisect_right
from typing import Iterator, List, Optional, Tuple

from dmlc_tpu.io.filesys import FileSystem, URI
from dmlc_tpu.io.recordio import (
    RECORDIO_MAGIC, RecordIOChunkReader, decode_flag, decode_length,
)
from dmlc_tpu.io.stream import SeekStream
from dmlc_tpu.io.uri_spec import URISpec
from dmlc_tpu.utils.logging import DMLCError, check, check_lt

__all__ = ["InputSplit", "list_split_files"]

_NEWLINE = b"\n\r"
_DEFAULT_CHUNK = 8 << 20  # 8 MiB — reference uses MB-scale chunk buffers
_MAGIC_BYTES = struct.pack("<I", RECORDIO_MAGIC)


def list_split_files(uri: str) -> List[Tuple[str, int]]:
    """Expand a (possibly ';'-joined, possibly directory) URI into
    [(path, size)] with size>0, sorted within each directory.

    Reference: InputSplitBase::Init's ListDirectory expansion.
    """
    spec = URISpec(uri)
    out: List[Tuple[str, int]] = []
    for path in spec.paths():
        u = URI(path)
        fs = FileSystem.get_instance(u)
        info = fs.get_path_info(u)
        if info.type == "directory":
            for fi in fs.list_directory(u):
                if fi.type == "file" and fi.size > 0:
                    out.append((fi.path, fi.size))
        elif info.size > 0:
            out.append((info.path, info.size))
    if not out:
        raise DMLCError(f"InputSplit: no non-empty input files match {uri!r}")
    return out


class InputSplit:
    """Pull-based reader over one shard of a sharded dataset.

    Reference: dmlc::InputSplit (include/dmlc/io.h) — NextRecord/NextChunk/
    BeforeFirst/ResetPartition/GetTotalSize. Create via :meth:`create`.
    """

    # -- factory

    @staticmethod
    def create(uri: str, part_index: int, num_parts: int,
               split_type: str = "text", *, chunk_size: int = _DEFAULT_CHUNK,
               shuffle: bool = False, seed: int = 0,
               batch_size: int = 256) -> "InputSplit":
        """Reference: InputSplit::Create (src/io.cc).

        split_type: "text" | "recordio" | "indexed_recordio".
        A '#cachefile' URI suffix wraps the split in a disk cache
        (reference: CachedInputSplit); shuffle applies to indexed_recordio
        (reference: input_split_shuffle.h does chunk shuffling for text —
        see dmlc_tpu.io.input_split_shuffle).
        """
        check_lt(part_index, num_parts, "part_index must be < num_parts")
        spec = URISpec(uri)
        if spec.uri == "-":
            check(split_type == "text",
                  f"stdin split supports only text records, "
                  f"not {split_type!r}")
            check(num_parts == 1,
                  "stdin split has exactly one part (a pipe cannot be "
                  "byte-range sharded)")
            return _StdinSplit(chunk_size=chunk_size)
        if split_type == "text":
            split: InputSplit = _TextSplit(uri, part_index, num_parts,
                                           chunk_size=chunk_size)
        elif split_type == "recordio":
            split = _RecordIOSplit(uri, part_index, num_parts,
                                   chunk_size=chunk_size)
        elif split_type == "indexed_recordio":
            from dmlc_tpu.io.indexed_recordio_split import IndexedRecordIOSplit
            split = IndexedRecordIOSplit(
                uri, part_index, num_parts, shuffle=shuffle, seed=seed,
                batch_size=batch_size)
        else:
            raise DMLCError(f"unknown split_type {split_type!r}")
        if spec.cache_file:
            from dmlc_tpu.io.cached_split import CachedInputSplit
            split = CachedInputSplit(split, spec.cache_file)
        return split

    # -- interface

    def next_record(self) -> Optional[bytes]:
        raise NotImplementedError

    def next_chunk(self) -> Optional[bytes]:
        """A buffer of whole records (zero or more chunks per shard)."""
        raise NotImplementedError

    def next_batch(self, n_records: int) -> Optional[List[bytes]]:
        """Up to n_records records; None at end of shard (reference:
        InputSplit::NextBatch, include/dmlc/io.h)."""
        check(n_records > 0,
              "next_batch(n_records) needs n_records >= 1: a zero-size "
              "request would be indistinguishable from end-of-shard (None)")
        out: List[bytes] = []
        while len(out) < n_records:
            rec = self.next_record()
            if rec is None:
                break
            out.append(rec)
        return out or None

    def before_first(self) -> None:
        raise NotImplementedError

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        raise NotImplementedError

    def get_total_size(self) -> int:
        raise NotImplementedError

    @property
    def bytes_read(self) -> int:
        raise NotImplementedError

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        """Split a chunk (as produced by next_chunk) into records."""
        raise NotImplementedError

    def __iter__(self) -> Iterator[bytes]:
        self.before_first()
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec


class _StdinSplit(InputSplit):
    """Degenerate single-part split over stdin (reference:
    src/io/single_file_split.h — the "-" URI path). One pass only;
    before_first after consumption raises (a pipe cannot rewind)."""

    rewindable = False  # a pipe cannot seek; parsers skip prefetch

    def __init__(self, chunk_size: int = _DEFAULT_CHUNK):
        self._consumed = False
        self._recbuf: List[bytes] = []
        self._recpos = 0
        self._bytes = 0
        self._chunk_size = max(chunk_size, 64 * 1024)
        self._leftover = b""
        self._eof = False

    def next_chunk(self) -> Optional[bytes]:
        """Bounded streaming read with partial-line carry — a piped
        50 GB stream never lives in memory at once."""
        import sys
        while not self._eof:
            self._consumed = True
            raw = sys.stdin.buffer.read(self._chunk_size)
            if not raw:
                self._eof = True
                break
            self._bytes += len(raw)
            combined = self._leftover + raw
            cut = max(combined.rfind(b"\n"), combined.rfind(b"\r")) + 1
            if cut == 0:
                self._leftover = combined
                continue
            self._leftover = combined[cut:]
            return combined[:cut]
        if self._leftover:
            tail, self._leftover = self._leftover, b""
            return tail
        return None

    def next_record(self) -> Optional[bytes]:
        while self._recpos >= len(self._recbuf):
            chunk = self.next_chunk()
            if chunk is None:
                return None
            self._recbuf = list(self.extract_records(chunk))
            self._recpos = 0
        rec = self._recbuf[self._recpos]
        self._recpos += 1
        return rec

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        for line in chunk.splitlines():
            if line:
                yield line

    def before_first(self) -> None:
        if not self._consumed:
            return  # fresh stream: nothing to rewind
        if self._recbuf:
            self._recpos = 0  # replay buffered records
        else:
            raise DMLCError("stdin split cannot rewind")

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        check(num_parts == 1, "stdin split has exactly one part")

    def get_total_size(self) -> int:
        return self._bytes

    @property
    def bytes_read(self) -> int:
        return self._bytes


class _AlignedSplitBase(InputSplit):
    """Byte-range sharding engine (reference: InputSplitBase)."""

    def __init__(self, uri: str, part_index: int, num_parts: int, *,
                 align_bytes: int, chunk_size: int):
        self._uri = uri
        self._files = list_split_files(uri)
        self._prefix = [0]
        for _, size in self._files:
            self._prefix.append(self._prefix[-1] + size)
        self._total = self._prefix[-1]
        self._align = align_bytes
        self._chunk_size = max(chunk_size, 64 * 1024)
        self._fs_cache: dict = {}
        self._bytes_read = 0
        self.reset_partition(part_index, num_parts)

    # -- shared machinery

    def _open_at(self, global_offset: int) -> Tuple[SeekStream, int, int]:
        """(stream positioned at global_offset, file_index, file_end_global)."""
        i = bisect_right(self._prefix, global_offset) - 1
        if i >= len(self._files):
            i = len(self._files) - 1
        path = self._files[i][0]
        u = URI(path)
        fs = FileSystem.get_instance(u)
        stream = fs.open_for_read(u)
        stream.seek(global_offset - self._prefix[i])
        return stream, i, self._prefix[i + 1]

    def _boundary(self, x: int) -> int:
        """First record start at-or-after raw offset x (the shared rule)."""
        if x <= 0:
            return 0
        if x >= self._total:
            return self._total
        i = bisect_right(self._prefix, x) - 1
        if x == self._prefix[i]:
            return x  # file boundary is a record boundary
        stream, _, file_end = self._open_at(x)
        try:
            skipped = self._seek_record_begin(stream)
        finally:
            stream.close()
        return min(x + skipped, file_end)

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        check_lt(part_index, num_parts)
        nstep = (self._total + num_parts - 1) // num_parts
        raw_begin = min(nstep * part_index, self._total)
        raw_end = min(nstep * (part_index + 1), self._total)
        if self._align > 1:
            raw_begin -= raw_begin % self._align
            raw_end -= raw_end % self._align
        self._begin = self._boundary(raw_begin)
        self._end = self._boundary(raw_end)
        self.part_index = part_index
        self.num_parts = num_parts
        self.before_first()

    def before_first(self) -> None:
        old = getattr(self, "_stream", None)
        if old is not None:
            old.close()
        self._cur = self._begin
        self._stream: Optional[SeekStream] = None
        self._file_end = 0
        self._leftover = b""
        self._record_buf: List[bytes] = []
        self._record_pos = 0
        self._bytes_read = 0

    def get_total_size(self) -> int:
        return self._total

    @property
    def bytes_read(self) -> int:
        return self._bytes_read

    def next_chunk(self) -> Optional[bytes]:
        """Next buffer of whole records within [begin, end)."""
        while True:
            if self._cur >= self._end and not self._leftover:
                return None
            if self._stream is None and self._cur < self._end:
                self._stream, _, self._file_end = self._open_at(self._cur)
            want = min(self._chunk_size,
                       self._file_end - self._cur,
                       self._end - self._cur)
            raw = self._stream.read(want) if want > 0 else b""
            if want > 0 and not raw:
                # EOF inside the recorded byte range: the backing file
                # SHRANK after the split captured its sizes. Without
                # this check the loop would spin forever re-reading 0
                # bytes (cur never advances to the recorded end).
                raise DMLCError(
                    f"InputSplit: unexpected EOF at global offset "
                    f"{self._cur} ({min(self._file_end, self._end) - self._cur} "
                    f"bytes short of the recorded range) — the backing "
                    f"file shrank after the split was created; recreate "
                    f"the split after mutating inputs")
            self._bytes_read += len(raw)
            self._cur += len(raw)
            at_file_end = self._cur >= min(self._file_end, self._end)
            combined = self._leftover + raw if self._leftover else raw
            if at_file_end:
                # file (or shard) exhausted: everything left is whole records
                self._stream.close()
                self._stream = None
                self._leftover = b""
                if self._cur >= self._end:
                    self._cur = self._end
                if combined:
                    return combined
                continue
            cut = self._find_last_record_end(combined)
            if cut == 0:
                # no complete record in buffer: grow it
                self._leftover = combined
                continue
            self._leftover = combined[cut:]
            return combined[:cut]

    def next_record(self) -> Optional[bytes]:
        while self._record_pos >= len(self._record_buf):
            chunk = self.next_chunk()
            if chunk is None:
                return None
            self._record_buf = list(self.extract_records(chunk))
            self._record_pos = 0
        rec = self._record_buf[self._record_pos]
        self._record_pos += 1
        return rec

    # -- format-specific hooks

    def _seek_record_begin(self, stream: SeekStream) -> int:
        """Bytes to skip from the stream position to the next record start
        (reference: LineSplitter/RecordIOSplitter::SeekRecordBegin)."""
        raise NotImplementedError

    def _find_last_record_end(self, buf: bytes) -> int:
        """Largest prefix length of buf consisting of whole records
        (reference: InputSplitBase::FindLastRecordBegin)."""
        raise NotImplementedError


class _TextSplit(_AlignedSplitBase):
    """Line records (reference: src/io/line_split.cc)."""

    def __init__(self, uri: str, part_index: int, num_parts: int, *,
                 chunk_size: int = _DEFAULT_CHUNK):
        super().__init__(uri, part_index, num_parts, align_bytes=1,
                         chunk_size=chunk_size)

    def _seek_record_begin(self, stream: SeekStream) -> int:
        nstep = 0
        found = False
        while True:
            buf = stream.read(64 * 1024)
            if not buf:
                return nstep
            i = 0
            if not found:
                jn = buf.find(b"\n")
                jr = buf.find(b"\r")
                j = min(x for x in (jn, jr) if x >= 0) if (jn >= 0 or jr >= 0) else -1
                if j < 0:
                    nstep += len(buf)
                    continue
                nstep += j + 1
                found = True
                i = j + 1
            while i < len(buf):
                if buf[i] in (10, 13):
                    nstep += 1
                    i += 1
                else:
                    return nstep
            # buffer ended inside newline run: keep scanning

    def _find_last_record_end(self, buf: bytes) -> int:
        n = max(buf.rfind(b"\n"), buf.rfind(b"\r"))
        return n + 1 if n >= 0 else 0

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        for line in chunk.splitlines():
            if line:
                yield line


class _RecordIOSplit(_AlignedSplitBase):
    """RecordIO frame records (reference: src/io/recordio_split.cc)."""

    def __init__(self, uri: str, part_index: int, num_parts: int, *,
                 chunk_size: int = _DEFAULT_CHUNK):
        super().__init__(uri, part_index, num_parts, align_bytes=4,
                         chunk_size=chunk_size)

    def _seek_record_begin(self, stream: SeekStream) -> int:
        """Scan 4-aligned words for a frame head that *starts* a record."""
        nstep = 0
        window = b""
        while True:
            buf = stream.read(64 * 1024)
            if not buf:
                return nstep + len(window)
            window += buf
            pos = 0
            while pos + 8 <= len(window):
                if window[pos:pos + 4] == _MAGIC_BYTES:
                    lrec = struct.unpack_from("<I", window, pos + 4)[0]
                    if decode_flag(lrec) in (0, 1):
                        return nstep + pos
                pos += 4
            nstep += pos
            window = window[pos:]

    def _find_last_record_end(self, buf: bytes) -> int:
        pos = 0
        complete_end = 0
        n = len(buf)
        in_multi = False
        while pos + 8 <= n:
            magic, lrec = struct.unpack_from("<II", buf, pos)
            check(magic == RECORDIO_MAGIC,
                  "RecordIO split: lost frame alignment")
            clen = decode_length(lrec)
            cflag = decode_flag(lrec)
            frame_end = pos + 8 + clen + ((-clen) % 4)
            if frame_end > n:
                break
            if cflag == 0:
                complete_end = frame_end
                in_multi = False
            elif cflag == 1:
                in_multi = True
            elif cflag == 3:
                check(in_multi, "RecordIO split: end-frame without start")
                complete_end = frame_end
                in_multi = False
            pos = frame_end
        return complete_end

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        return iter(RecordIOChunkReader(chunk))
