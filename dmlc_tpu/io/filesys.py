"""Virtual filesystem registry with URI-scheme dispatch.

Reference: src/io/filesys.{h,cc} — FileSystem::GetInstance(URI),
Open/OpenForRead/GetPathInfo/ListDirectory, URI{protocol,host,name},
FileInfo{path,size,type}; src/io/local_filesys.{h,cc}.

Cloud backends (S3/HDFS/Azure, reference src/io/{s3,hdfs,azure}_filesys.cc)
are a plugin seam here: the schemes are pre-registered with stub factories
that raise an informative error telling the user how to register a real
implementation (this environment has no libcurl/libhdfs — documented
non-goal, see SURVEY.md §7). A real backend registers via
``FileSystem.register_scheme``.
"""

from __future__ import annotations

import os
import stat as _stat
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from dmlc_tpu.resilience.policy import guarded
from dmlc_tpu.utils.logging import DMLCError, check
from dmlc_tpu.io.stream import FileStream, SeekStream, Stream

__all__ = ["URI", "FileInfo", "FileSystem", "LocalFileSystem"]


class URI:
    """Parsed resource locator (reference: io::URI{protocol, host, name}).

    ``s3://bucket/key`` → protocol "s3://", host "bucket", name "/key".
    A bare path has protocol "file://".
    """

    __slots__ = ("protocol", "host", "name")

    def __init__(self, uri: str):
        if "://" not in uri:
            self.protocol = "file://"
            self.host = ""
            self.name = uri
        else:
            proto, _, rest = uri.partition("://")
            self.protocol = proto + "://"
            if self.protocol == "file://":
                self.host = ""
                self.name = rest
            else:
                host, slash, path = rest.partition("/")
                self.host = host
                self.name = slash + path
        check(self.name != "" or self.host != "", f"invalid URI {uri!r}")

    def str_uri(self) -> str:
        if self.protocol == "file://":
            return self.name
        return f"{self.protocol}{self.host}{self.name}"

    def __repr__(self) -> str:
        return f"URI({self.str_uri()!r})"


@dataclass
class FileInfo:
    """Reference: FileInfo{path, size, type}. ``mtime_ns`` extends the
    reference shape so fingerprint stamps (io/pagestore.py) can stat
    any registered scheme through one seam; backends without a
    modification clock report 0."""
    path: str
    size: int
    type: str  # "file" | "directory"
    mtime_ns: int = 0


class FileSystem:
    """Abstract VFS + scheme registry (reference: dmlc::io::FileSystem)."""

    _schemes: Dict[str, Callable[[], "FileSystem"]] = {}
    _instances: Dict[str, "FileSystem"] = {}

    # -- registry

    @classmethod
    def register_scheme(cls, protocol: str,
                        factory: Callable[[], "FileSystem"]) -> None:
        """Register a filesystem factory for a protocol like "s3://"."""
        check(protocol.endswith("://"), f"protocol must end with ://: {protocol!r}")
        cls._schemes[protocol] = factory
        cls._instances.pop(protocol, None)

    @classmethod
    def get_instance(cls, uri: URI,
                     allow_null: bool = False) -> Optional["FileSystem"]:
        """Reference: FileSystem::GetInstance — protocol → singleton."""
        inst = cls._instances.get(uri.protocol)
        if inst is not None:
            return inst
        factory = cls._schemes.get(uri.protocol)
        if factory is None:
            if allow_null:
                return None
            raise DMLCError(
                f"unknown filesystem protocol {uri.protocol!r}; registered: "
                f"{sorted(cls._schemes)}")
        inst = factory()
        cls._instances[uri.protocol] = inst
        return inst

    # -- interface

    def open(self, uri: URI, mode: str) -> Stream:
        raise NotImplementedError

    def open_for_read(self, uri: URI) -> SeekStream:
        raise NotImplementedError

    def get_path_info(self, uri: URI) -> FileInfo:
        raise NotImplementedError

    def list_directory(self, uri: URI) -> List[FileInfo]:
        raise NotImplementedError


class LocalFileSystem(FileSystem):
    """Local files (reference: src/io/local_filesys.cc)."""

    def open(self, uri: URI, mode: str) -> FileStream:
        check(mode in ("r", "w", "a"), f"invalid mode {mode!r}")
        return FileStream(open(uri.name, mode + "b"), path=uri.name)

    def open_for_read(self, uri: URI) -> FileStream:
        return FileStream(open(uri.name, "rb"), path=uri.name)

    def get_path_info(self, uri: URI) -> FileInfo:
        # resilience seam io.filesys.stat (retry policy + fault plan)
        st = guarded("io.filesys.stat", lambda: os.stat(uri.name))
        ftype = "directory" if _stat.S_ISDIR(st.st_mode) else "file"
        return FileInfo(path=uri.name, size=st.st_size, type=ftype,
                        mtime_ns=st.st_mtime_ns)

    def list_directory(self, uri: URI) -> List[FileInfo]:
        def scan() -> List[FileInfo]:
            out = []
            for name in sorted(os.listdir(uri.name)):
                full = os.path.join(uri.name, name)
                st = os.stat(full)
                ftype = ("directory" if _stat.S_ISDIR(st.st_mode)
                         else "file")
                out.append(FileInfo(path=full, size=st.st_size,
                                    type=ftype, mtime_ns=st.st_mtime_ns))
            return out
        return guarded("io.filesys.list", scan)


class _StubFileSystem(FileSystem):
    """Pre-registered cloud scheme with no backend in this build.

    Reference equivalents (s3/hdfs/azure filesystems) need libcurl/libhdfs,
    absent here by design; a real implementation plugs in via
    ``FileSystem.register_scheme``.
    """

    def __init__(self, protocol: str, hint: str):
        self.protocol = protocol
        self.hint = hint

    def _fail(self):
        raise DMLCError(
            f"filesystem {self.protocol!r} has no backend in this build "
            f"({self.hint}). Register one with FileSystem.register_scheme"
            f"({self.protocol!r}, factory).")

    def open(self, uri, mode):
        self._fail()

    def open_for_read(self, uri):
        self._fail()

    def get_path_info(self, uri):
        self._fail()

    def list_directory(self, uri):
        self._fail()


FileSystem.register_scheme("file://", LocalFileSystem)
for _proto, _hint in (("s3://", "reference: src/io/s3_filesys.cc, needs HTTP+HMAC"),
                      ("hdfs://", "reference: src/io/hdfs_filesys.cc, needs libhdfs"),
                      ("azure://", "reference: src/io/azure_filesys.cc"),
                      ("gs://", "GCS plugin seam (no reference counterpart)")):
    FileSystem.register_scheme(
        _proto, (lambda p=_proto, h=_hint: _StubFileSystem(p, h)))
