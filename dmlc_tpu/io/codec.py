"""ONE compressed-page codec for every on-disk/on-wire page byte.

ROADMAP item 5 names "optional page compression (trade CPU for I/O)"
as exactly what the NVMe spill tier and item 1's remote wire want.
This module is that trade as ONE seam: a zlib-backed page codec with a
self-describing header, applied at the three places that already share
the unified page store —

- ``RoundSpillWriter`` round pages (``data/row_iter.py``): steady spill
  replay reads fewer NVMe bytes per round;
- hydrated remote blocks (``io/objstore/fs.py`` → ``io/pagestore.py``
  entries, the sidecar stamps the codec): the NVMe cache holds fewer
  bytes per object;
- the objstore wire itself (``EmulatedObjectStore.get_encoded``): a
  cold ``obj://`` epoch moves fewer wire bytes, decompressed under the
  existing ``io.objstore.get`` retry seam and counted honestly
  (``dmlc_objstore_bytes_total`` = compressed on-wire bytes,
  ``dmlc_objstore_bytes_served_total`` = decompressed payload).

Page frame (little-endian, 20-byte header)::

    magic  u32  0x43505444 ("DTPC")
    ver    u8   1
    codec  u8   0 = stored (raw payload), 1 = zlib
    level  u16  zlib level (0 for stored)
    rawlen u64  decoded payload length
    crc    u32  zlib.crc32 of the decoded payload
    <payload>

Contract (pinned by tests/test_codec.py):

- ``decode_page(encode_page(x, level)) == x`` for every level and every
  input — level 0 is a raw PASSTHROUGH (bytes unchanged) unless the
  input itself begins with the frame magic, which is wrapped in a
  stored frame so decode stays unambiguous;
- incompressible input (already-compressed data, random bytes) never
  grows more than the 20-byte header: when zlib does not shrink the
  page, the encoder falls back to a stored frame (or the passthrough);
- ``decode_page`` of a corrupt frame — bad version/codec id, truncated
  payload, a crc or length mismatch, undecompressable bytes — raises
  :class:`~dmlc_tpu.utils.logging.DMLCError`, never returns shifted or
  partial bytes (the retry seams rely on that);
- bytes that do not start with the magic pass through ``decode_page``
  unchanged, so raw legacy pages stay readable.

``zlib``/``gzip``/``bz2``/``lzma`` imports anywhere else in
``dmlc_tpu/`` are forbidden by the scripts/lint.py codec gate (the one
pinned exception: ``resilience/policy.py``'s ``zlib.crc32`` jitter
hash) — compression is a seam, not a per-call-site choice.

Enable globally with ``DMLC_TPU_PAGE_CODEC_LEVEL=<1..9>`` (0 = raw,
the default). When to enable: see docs/remote_io.md — compression pays
when the epoch is wire- or NVMe-bound (``/analyze`` verdict ``wire``),
and costs when it is already CPU-bound (``parse``/``assemble``).
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Optional

from dmlc_tpu.utils.logging import DMLCError, check

__all__ = ["encode_page", "decode_page", "is_encoded", "default_level",
           "tag", "PAGE_CODEC_MAGIC", "ENV_LEVEL", "HEADER_BYTES"]

PAGE_CODEC_MAGIC = 0x43505444  # b"DTPC" little-endian
ENV_LEVEL = "DMLC_TPU_PAGE_CODEC_LEVEL"

_HDR = struct.Struct("<IBBHQI")  # magic, ver, codec, level, rawlen, crc
HEADER_BYTES = _HDR.size
_MAGIC_BYTES = struct.pack("<I", PAGE_CODEC_MAGIC)
_VERSION = 1
_CODEC_STORED = 0
_CODEC_ZLIB = 1


def default_level() -> int:
    """The process default codec level: ``DMLC_TPU_PAGE_CODEC_LEVEL``
    clamped to [0, 9]; 0 (raw) on unset or unparseable."""
    env = os.environ.get(ENV_LEVEL)
    if not env:
        return 0
    try:
        return max(0, min(9, int(env)))
    except ValueError:
        return 0


def tag(level: int) -> str:
    """The sidecar/meta codec stamp for a level: "raw" or "zlib:N"."""
    return "raw" if level <= 0 else f"zlib:{int(level)}"


def is_encoded(data: bytes) -> bool:
    """Whether ``data`` carries the self-describing page frame."""
    return len(data) >= 4 and bytes(data[:4]) == _MAGIC_BYTES


def _frame(codec: int, level: int, raw: bytes, payload: bytes) -> bytes:
    return _HDR.pack(PAGE_CODEC_MAGIC, _VERSION, codec, level,
                     len(raw), zlib.crc32(raw)) + payload


def encode_page(data, level: Optional[int] = None) -> bytes:
    """Encode one page. ``level`` None resolves :func:`default_level`;
    0 is the raw passthrough (bytes unchanged — except raw input that
    itself starts with the frame magic, which is wrapped in a stored
    frame so :func:`decode_page` stays unambiguous). Levels 1-9
    compress with zlib, falling back to a stored frame when the page
    does not shrink (incompressible input)."""
    data = bytes(data)
    if level is None:
        level = default_level()
    check(0 <= level <= 9, f"codec: bad zlib level {level}")
    if level <= 0:
        if is_encoded(data):
            return _frame(_CODEC_STORED, 0, data, data)
        return data
    comp = zlib.compress(data, level)
    if len(comp) + HEADER_BYTES < len(data):
        return _frame(_CODEC_ZLIB, level, data, comp)
    if is_encoded(data):
        return _frame(_CODEC_STORED, 0, data, data)
    return data


def decode_page(data) -> bytes:
    """Decode one page: framed pages are validated (version, codec id,
    length, crc) and decompressed; anything else passes through
    unchanged (raw pages stay readable). A corrupt or truncated frame
    raises DMLCError — never shifted/partial bytes."""
    data = bytes(data)
    if not is_encoded(data):
        return data
    check(len(data) >= HEADER_BYTES,
          f"codec: truncated page header ({len(data)} of "
          f"{HEADER_BYTES} bytes)")
    magic, ver, codec, level, rawlen, crc = _HDR.unpack_from(data)
    check(ver == _VERSION, f"codec: unknown page version {ver}")
    payload = data[HEADER_BYTES:]
    if codec == _CODEC_STORED:
        raw = payload
    elif codec == _CODEC_ZLIB:
        try:
            raw = zlib.decompress(payload)
        except zlib.error as e:
            raise DMLCError(f"codec: corrupt compressed page ({e})") \
                from e
    else:
        raise DMLCError(f"codec: unknown codec id {codec}")
    check(len(raw) == rawlen,
          f"codec: decoded length {len(raw)} != recorded {rawlen} "
          "(truncated or torn page)")
    check(zlib.crc32(raw) == crc,
          "codec: page crc mismatch (corrupt payload)")
    return raw
