"""EOF-less streaming input over a growing file.

Every split before this module treats its byte range as FROZEN: the
sizes captured at create are the epoch, EOF is the end, and a mutated
backing file is an error (the PR 1 shrink detection). A serving-shaped
system ingests the opposite thing — an append-only file (a log, a
feed dump, a producer's staging file) that GROWS while the pipeline
runs. :class:`StreamingSplit` is the InputSplit-shaped reader for that
source:

- **EOF-less**: ``next_chunk()`` polls the source's size (through the
  scheme-aware ``stat_uri`` seam, so ``obj://`` objects stream too)
  and blocks until new whole records exist, instead of returning None
  at the frozen end.
- **Windowed**: appended records accumulate into a *window* closed by
  ``window_records`` (count) and/or ``window_s`` (time since the
  window opened) — one ``next_chunk()`` == one window, feeding the
  unchanged parse/batch/to_device machinery.
- **Watermarked**: the split carries a monotonic watermark — committed
  byte offset, records delivered, windows closed, and the wall time of
  the last advance — surfaced via :meth:`watermark` (pipeline probes stamp
  it into stage extras; the multi-tenant ``/tenants`` rows render it).
- **Mutation-safe**: every read re-opens the source at the COMMITTED
  offset (the last delivered record boundary) through the
  ``io.stream.read`` resilience seam. A short or failed read (an
  injected ``truncate``/``ioerror`` fault, a racing writer) is a clean
  windowed retry — the next poll re-reads from the committed boundary,
  so downstream bytes are never shifted. Only a source that actually
  SHRANK below the committed offset raises (that is a rewrite, not an
  append).

Termination contract (streams do not end, epochs must): ``stop()``
drains what is committed-readable and ends the stream;
``idle_timeout_s`` ends it after that long with no growth (None =
block forever). A consumed split cannot rewind (``rewindable=False``,
the stdin-split precedent) — parsers skip their chunk-prefetch thread
and pull synchronously.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from dmlc_tpu.io.input_split import InputSplit
from dmlc_tpu.io.stream import create_seek_stream_for_read
from dmlc_tpu.obs.metrics import REGISTRY as _METRICS
from dmlc_tpu.utils.logging import DMLCError, check

__all__ = ["StreamingSplit"]

_NEWLINE = b"\n\r"


class StreamingSplit(InputSplit):
    """Pull-based EOF-less reader over ONE growing text source.

    A growing file cannot be byte-range sharded (the range is still
    being written), so a StreamingSplit is always one part — gangs
    stream distinct URIs, or fan one stream out downstream.
    """

    rewindable = False  # a stream cannot seek back; parsers skip prefetch

    def __init__(self, uri: str, *,
                 window_records: Optional[int] = None,
                 window_s: Optional[float] = None,
                 poll_interval_s: float = 0.05,
                 idle_timeout_s: Optional[float] = None,
                 chunk_size: int = 8 << 20):
        check(window_records is None or window_records >= 1,
              "StreamingSplit: window_records must be >= 1")
        check(window_s is None or window_s > 0,
              "StreamingSplit: window_s must be > 0")
        check(poll_interval_s > 0,
              "StreamingSplit: poll_interval_s must be > 0")
        self.uri = uri
        self._window_records = window_records
        self._window_s = window_s
        self._poll_s = float(poll_interval_s)
        self._idle_s = idle_timeout_s
        self._chunk_size = max(int(chunk_size), 64 * 1024)
        self._committed = 0          # byte offset of the last delivered
        #                              record boundary (the watermark)
        self._records = 0
        self._windows = 0
        self._bytes_read = 0
        self._retries = 0            # degraded polls (short/failed read)
        self._last_advance = time.time()
        self._consumed = False
        self._stop = threading.Event()
        self._ended = False
        self._record_buf: List[bytes] = []
        self._record_pos = 0
        # the watermark is live telemetry: one registry snapshot sees
        # every stream's progress next to queue/engine stats (weakly
        # registered — a dropped split leaves on its own)
        self._metrics_key = _METRICS.register(
            f"stream/{uri}", self, StreamingSplit.watermark)

    # -- control / telemetry

    def stop(self) -> None:
        """End the stream: the current poll drains whatever whole
        records are already on disk, then ``next_chunk`` returns None."""
        self._stop.set()

    def watermark(self) -> Dict[str, Any]:
        """The monotonic watermark + degradation counters (the shape
        pipeline probes stamp into ``extra["stream"]``; also the
        registered ``stream/<uri>`` metrics collector)."""
        return {
            "uri": self.uri,
            "watermark_bytes": self._committed,
            "watermark_records": self._records,
            "windows": self._windows,
            "retries": self._retries,
            "last_advance_s_ago": round(
                time.time() - self._last_advance, 3),
            "ended": self._ended,
        }

    # -- polling machinery

    def _size(self) -> Optional[int]:
        """Current source size through the scheme-aware stat seam;
        None on a transient stat failure (counted, retried next poll)."""
        from dmlc_tpu.io.pagestore import stat_uri
        try:
            return stat_uri(self.uri)[0]
        except (OSError, DMLCError):
            self._retries += 1
            return None

    def _read_from_committed(self, size: int) -> bytes:
        """One bounded read starting at the committed record boundary.
        Opens fresh each poll (the file is being appended; a held
        stream's EOF state would go stale) and reads through the
        ``io.stream.read`` resilience seam. Short reads — an injected
        truncate fault, a racing writer — return what arrived; the
        next poll re-reads from the same committed boundary, so a
        degraded read can never shift downstream bytes."""
        want = min(size - self._committed, self._chunk_size)
        if want <= 0:
            return b""
        try:
            stream = create_seek_stream_for_read(self.uri)
            try:
                stream.seek(self._committed)
                data = stream.read(want)
            finally:
                stream.close()
        except (OSError, DMLCError):
            self._retries += 1
            return b""
        if len(data) < want:
            # the source answered short of its own stat — a torn poll
            # (fault injection pins the stream at EOF; a writer may be
            # mid-append). Keep the whole records that DID arrive; the
            # rest re-reads next poll from the committed boundary.
            self._retries += 1
        return data

    @staticmethod
    def _last_record_end(buf: bytes) -> int:
        n = max(buf.rfind(b"\n"), buf.rfind(b"\r"))
        return n + 1 if n >= 0 else 0

    @staticmethod
    def _count_records(buf: bytes) -> int:
        return sum(1 for line in buf.splitlines() if line)

    def next_chunk(self) -> Optional[bytes]:
        """One WINDOW of whole appended records, blocking until the
        window closes (count/time), the stream is stopped (drain, then
        None), or ``idle_timeout_s`` passes with no growth (None)."""
        if self._ended:
            return None
        self._consumed = True
        window: List[bytes] = []
        win_records = 0
        win_opened: Optional[float] = None
        idle_since = time.monotonic()
        seen_size = self._committed   # raw growth resets the idle clock
        draining = False              # idle expiry: one stop-style pass
        drain_retries = 0             # faulted polls tolerated at stop
        while True:
            stopping = self._stop.is_set() or draining
            size = self._size()
            grew = False
            if size is not None and size < self._committed:
                raise DMLCError(
                    f"StreamingSplit: source {self.uri!r} shrank to "
                    f"{size} bytes below the committed offset "
                    f"{self._committed} — a streaming source must be "
                    "append-only (rewrites need a fresh split)")
            if size is not None and size > seen_size:
                # RAW byte growth (even mid-record) proves the writer
                # is alive: a slow writer trickling one long line must
                # not be idle-timed out and have its half-line drained
                seen_size = size
                idle_since = time.monotonic()
            if size is not None and size > self._committed:
                data = self._read_from_committed(size)
                cut = self._last_record_end(data)
                if (stopping and cut == 0 and data
                        and len(data) == size - self._committed):
                    # final drain, and the read reached the source's
                    # true end (not a short/faulted or chunk-clipped
                    # read whose record continues on disk): a last
                    # record with no trailing newline is still a whole
                    # record once the writer stopped (the finite-file
                    # epoch would parse it)
                    cut = len(data)
                if cut == 0 and len(data) >= self._chunk_size:
                    # a full buffer without one record boundary: the
                    # record is larger than the poll buffer and no
                    # amount of re-polling can commit it — fail loud
                    # instead of silently re-reading 8 MB per poll
                    # forever (or dropping it at idle timeout)
                    raise DMLCError(
                        f"StreamingSplit: a record at offset "
                        f"{self._committed} of {self.uri!r} exceeds "
                        f"chunk_size={self._chunk_size} bytes; raise "
                        "chunk_size past the longest record")
                if cut > 0:
                    piece = data[:cut]
                    self._committed += cut
                    self._bytes_read += cut
                    n = self._count_records(piece)
                    self._records += n
                    self._last_advance = time.time()
                    idle_since = time.monotonic()
                    grew = True
                    window.append(piece)
                    win_records += n
                    if win_opened is None:
                        win_opened = time.monotonic()
            # window-close rules
            if window:
                full = (self._window_records is not None
                        and win_records >= self._window_records)
                timed = (self._window_s is not None
                         and time.monotonic() - win_opened
                         >= self._window_s)
                unbounded = (self._window_records is None
                             and self._window_s is None)
                if full or timed or stopping or unbounded:
                    self._windows += 1
                    if draining:
                        # idle drain delivers at most one last window
                        self._ended = True
                    return b"".join(window)
            if stopping and not grew:
                if (size is not None and size > self._committed
                        and drain_retries < 50):
                    # readable bytes remain but this poll came back
                    # short/failed (an injected truncate, a transient
                    # error): the stop drain re-polls — ending here
                    # would DROP committed-readable records. Bounded,
                    # so a permanently failing source still ends.
                    drain_retries += 1
                    time.sleep(self._poll_s)
                    continue
                if size is not None and size > self._committed:
                    from dmlc_tpu.obs.log import warn_limited
                    warn_limited(
                        "streaming-drain-gave-up",
                        f"StreamingSplit: stop drain of {self.uri!r} "
                        f"gave up with {size - self._committed} "
                        "unreadable bytes after 50 failed polls",
                        min_interval_s=10.0)
                # stopped and drained: stream over
                self._ended = True
                return b"".join(window) if window else None
            if (self._idle_s is not None and not grew and not draining
                    and time.monotonic() - idle_since >= self._idle_s):
                # the writer went quiet: take ONE stop-style drain
                # pass (an unterminated final line commits exactly
                # like stop() — the finite-file epoch would parse it),
                # then end
                draining = True
                continue
            time.sleep(self._poll_s)

    # -- InputSplit surface

    def next_record(self) -> Optional[bytes]:
        while self._record_pos >= len(self._record_buf):
            chunk = self.next_chunk()
            if chunk is None:
                return None
            self._record_buf = list(self.extract_records(chunk))
            self._record_pos = 0
        rec = self._record_buf[self._record_pos]
        self._record_pos += 1
        return rec

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        for line in chunk.splitlines():
            if line:
                yield line

    def before_first(self) -> None:
        if not self._consumed:
            return  # fresh stream: nothing to rewind
        raise DMLCError(
            "StreamingSplit cannot rewind: a stream has no beginning "
            "to return to (create a fresh split for a new run)")

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        check(num_parts == 1,
              "StreamingSplit has exactly one part (a growing file "
              "cannot be byte-range sharded)")

    def get_total_size(self) -> int:
        return self._committed

    @property
    def bytes_read(self) -> int:
        return self._bytes_read
