"""Checkpoint/resume: device-buffer round-trips over Streams.

Reference: the primitives in include/dmlc/io.h (Stream::Write/Read,
dmlc::Serializable) + serializer.h + JSON metadata — the reference ships
the mechanism, downstream (XGBoost SaveModel) composes it. Here the
composition is provided too, TPU-natively:

- ``save_pytree``/``load_pytree``: any pytree of arrays ↔ one Stream
  (single-host path; works with np and jax arrays).
- ``ShardedCheckpoint``: multi-host jax.Arrays — each process writes ONLY
  its addressable shards to its own stream (`ckpt-<step>/shard-<pid>.bin`
  + a tiny `shard-<pid>.idx.json` byte index + `meta.json`), and restore
  reads ONLY the shard records whose placements intersect this process's
  addressable device slices (seeking via the index), assembling each
  device's slice and building the global array with
  jax.make_array_from_single_device_arrays. Peak host memory on restore
  is ~this process's shard bytes, not the global model size — the
  "checkpoints never touch (other hosts') DRAM" north star — and
  ``last_restore_bytes_read`` exposes the accounting (asserted in
  tests/test_checkpoint.py). Restoring to a different device count or
  sharding is legal: placements, not mesh topology, drive assembly.
  Writes are atomic (tmp + rename) and committed by a marker file so a
  torn save is never restored.

Device-direct remote checkpoints (ROADMAP item 4): a root containing
``://`` (``obj://bucket/prefix``) switches ``ShardedCheckpoint`` to
the object-store plane — per-shard payloads stream straight to
``obj://`` through the multipart writer (io/objstore/multipart.py),
never staging the whole tree on the host:

- every shard record is a CONTENT-ADDRESSED page object
  ``<root>/pages/<digest>.pg`` (digest over dtype/shape/bytes), so an
  incremental save re-uploads ONLY changed shards: unchanged digests
  are recognized from the local page store's committed
  ``ckptpg-<digest>.pages`` entries (or a HEAD probe) and reused
  without re-serializing — ``last_save_bytes_written`` vs
  ``last_save_bytes_reused`` is the accounting;
- each writer publishes ``<step>/shard-<w>.idx.json`` (key, placement,
  digest, nbytes per record); writer 0 waits for ``num_writers`` index
  files, writes ``meta.json``, then the ``COMMIT`` marker — torn or
  in-flight saves are never restorable, exactly like the local swap;
- restore fans out over the gang: every member maps each digest to a
  content owner (``rendezvous/elastic.py``'s pure
  ``content_owner(digest, world)`` — any world size, so an N-writer
  checkpoint restores on M ranks with no negotiation), wire-fetches
  its OWN pages into the page store, and takes the rest from the
  owners' ``/pages`` tier + singleflight — each rank pays ~1/N of the
  wire (``checkpoint.restore.{local,peer,wire}_bytes`` counters prove
  the split; bench_suite config 21 measures it). Without a gang the
  same path degrades to all-wire, same bytes as today.
"""

from __future__ import annotations

import functools
import hashlib
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dmlc_tpu.io.stream import MemoryStream, create_stream
from dmlc_tpu.obs import trace as _trace
from dmlc_tpu.resilience.policy import guarded
from dmlc_tpu.utils import serializer as ser
from dmlc_tpu.utils.json_util import json_dump, json_load
from dmlc_tpu.utils.logging import DMLCError, check, check_eq

__all__ = ["save_pytree", "load_pytree", "ShardedCheckpoint"]

_FORMAT_VERSION = 1

# local page-store namespace for content-addressed checkpoint pages:
# fingerprint=None entries (immortal to the stale sweep, servable by
# the gang /pages tier as-is — obs/serve.py serves any committed
# sidecar-stamped entry)
_PAGE_PREFIX = "ckptpg-"


def _ckpt_count(which: str, n: int = 1) -> None:
    try:
        from dmlc_tpu.obs.metrics import REGISTRY
        REGISTRY.counter(f"checkpoint.{which}").inc(n)
    except Exception:  # noqa: BLE001 — telemetry must not break I/O
        pass


def _obj_count(which: str, n: int = 1) -> None:
    try:
        from dmlc_tpu.obs.metrics import REGISTRY
        REGISTRY.counter(f"objstore.{which}").inc(n)
    except Exception:  # noqa: BLE001 — telemetry must not break I/O
        pass


def _spanned(name: str):
    """Record the call as one obs trace span (no-op when tracing is
    off) — checkpoint save/restore shows up on the timeline next to
    the pipeline's pulls, so "epoch N was slow" and "epoch N contained
    a checkpoint" stop being separate investigations."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _trace.span(name, "checkpoint"):
                return fn(*args, **kwargs)
        return wrapper
    return deco


def _intersect(a: tuple, b: tuple) -> Optional[tuple]:
    """Intersection of two per-dim (start, stop) span tuples; None when
    empty. Scalars (zero-dim, empty tuples) always intersect."""
    out = []
    for (alo, ahi), (blo, bhi) in zip(a, b):
        lo, hi = max(alo, blo), min(ahi, bhi)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


def _flatten(tree: Any) -> Tuple[List[Tuple[str, Any]], Any]:
    import jax
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path) or "<root>"
        out.append((key, leaf))
    return out, treedef


@_spanned("checkpoint.save_pytree")
def save_pytree(tree: Any, uri: str) -> None:
    """Serialize a pytree of arrays to one stream (single-host path).

    The whole write is a resilience seam (site ``checkpoint.save``):
    idempotent, so a transient I/O failure rewrites from scratch under
    the site's RetryPolicy. (ShardedCheckpoint's multi-process save is
    NOT op-level retried — its barriers forbid solo re-entry — but its
    per-shard streams ride the io.stream.* seams.)"""
    leaves, _ = _flatten(tree)

    def write() -> None:
        with create_stream(uri, "w") as s:
            ser.write_u32(s, _FORMAT_VERSION)
            ser.write_u64(s, len(leaves))
            for key, leaf in leaves:
                ser.write_str(s, key)
                ser.write_ndarray(s, np.asarray(leaf))

    guarded("checkpoint.save", write)


@_spanned("checkpoint.load_pytree")
def load_pytree(uri: str, like: Optional[Any] = None) -> Any:
    """Load a checkpoint; returns {key: array}, or the structure of
    ``like`` when given (keys must match)."""
    def read() -> Dict[str, np.ndarray]:
        with create_stream(uri, "r") as s:
            version = ser.read_u32(s)
            check_eq(version, _FORMAT_VERSION,
                     "checkpoint version mismatch")
            n = ser.read_u64(s)
            out: Dict[str, np.ndarray] = {}
            for _ in range(n):
                key = ser.read_str(s)
                out[key] = ser.read_ndarray(s)
        return out

    # resilience seam checkpoint.restore: a transient read failure
    # re-reads the whole (immutable) file under the site's policy
    flat = guarded("checkpoint.restore", read)
    if like is None:
        return flat
    import jax
    leaves, treedef = _flatten(like)
    missing = [k for k, _ in leaves if k not in flat]
    if missing:
        raise DMLCError(f"checkpoint missing keys {missing}")
    return jax.tree_util.tree_unflatten(
        treedef, [flat[k] for k, _ in leaves])


class ShardedCheckpoint:
    """Per-process shard streams for global jax.Arrays (multi-host).

    Layout: ``<root>/step-<N>/shard-<pid>.bin`` + ``meta.json`` (written
    by process 0) + ``COMMIT`` marker. Each shard file holds, per leaf,
    the process's addressable shards (device index in the global device
    list, shard numpy data).
    """

    def __init__(self, root: str):
        self.root = root.rstrip("/") if "://" in root else root
        self.last_restore_bytes_read = 0  # data bytes read by restore()
        self.last_save_bytes_written = 0  # payload bytes uploaded
        self.last_save_bytes_reused = 0   # payload bytes deduped away
        # split of last_restore_bytes_read by source tier (remote roots)
        self.last_restore_local_bytes = 0
        self.last_restore_peer_bytes = 0
        self.last_restore_wire_bytes = 0
        self._remote = "://" in root
        if self._remote:
            from dmlc_tpu.io.filesys import URI
            u = URI(self.root)
            self._bucket = u.host
            self._obj_prefix = u.name.strip("/")
        else:
            os.makedirs(root, exist_ok=True)

    # -- paths

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.root, f"step-{step:08d}")

    def _committed(self, d: str) -> bool:
        return os.path.exists(os.path.join(d, "COMMIT"))

    def _resolve_step_dir(self, step: int) -> str:
        """Committed directory for a step. Every save writes into
        ``step-N.new`` and swaps it in only once fully committed; if a
        crash interrupted the swap, the committed ``.new`` IS the step —
        the previously committed data is never the casualty.

        When BOTH are committed (crash between .new's COMMIT and the
        swap renames), the .new wins: save() strips COMMIT from .new
        before reusing it, so a committed .new is always the newer save
        of this step (ADVICE r4). Which copy a step resolves to is then
        stable across time — the next save's swap promotes the same one
        restore has been serving."""
        d = self._step_dir(step)
        if self._committed(d + ".new"):
            return d + ".new"
        if self._committed(d):
            return d
        return d  # caller's commit check reports the right error

    def _committed_steps(self) -> List[int]:
        if self._remote:
            return self._committed_steps_remote()
        steps = set()
        for name in os.listdir(self.root):
            if not name.startswith("step-"):
                continue
            base = name.split("-", 1)[1]
            if base.endswith(".new"):
                base = base[:-len(".new")]
            try:
                step = int(base)
            except ValueError:
                continue
            if self._committed(self._resolve_step_dir(step)):
                steps.add(step)
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self._committed_steps()
        return steps[-1] if steps else None

    def all_steps(self) -> List[int]:
        return self._committed_steps()

    # -- save

    @_spanned("checkpoint.save")
    def save(self, step: int, tree: Any,
             metadata: Optional[Dict[str, Any]] = None,
             writer: Optional[int] = None,
             num_writers: Optional[int] = None) -> str:
        if self._remote:
            return self._save_remote(step, tree, metadata, writer,
                                     num_writers)
        check(writer is None and num_writers is None,
              "checkpoint: writer/num_writers apply to remote (obj://) "
              "roots; local saves shard by jax.process_index()")
        import jax
        pid = jax.process_index()
        leaves, _ = _flatten(tree)
        final = self._step_dir(step)
        # Every save builds in step-N.new and swaps it in only after ITS
        # commit. Unconditionally: the target must not depend on local
        # filesystem state (is step-N committed?), because on a shared FS
        # with attribute/negative-dentry caching (NFS) ranks can disagree
        # on that answer and scatter their shards across two directories
        # (ADVICE r3). A state-independent choice needs no agreement. The
        # swap also keeps re-saves crash-safe: the last committed
        # checkpoint survives a crash at any point, and restore
        # recognizes a committed .new as the step (ADVICE r2).
        d = final + ".new"
        # A crash between the commit-time renames can leave the step's
        # ONLY committed copy in step-N.new (final absent or stale).
        # Finish that swap before touching .new — otherwise the cleanup
        # below would strip COMMIT from the only committed copy and a
        # second crash during this save would lose the checkpoint.
        if pid == 0 and self._committed(d):
            self._swap_in(final)
        self._barrier()  # .new is settled before anyone creates into it
        existed = os.path.isdir(d)
        os.makedirs(d, exist_ok=True)
        if pid == 0 and existed:
            # stale uncommitted leftovers (torn save or torn re-save):
            # invalidate NOW and drop shard files of pids outside the new
            # world so restore cannot mix worlds
            commit = os.path.join(d, "COMMIT")
            if os.path.exists(commit):
                os.remove(commit)
            world = jax.process_count()
            for name in os.listdir(d):
                if not name.startswith("shard-"):
                    continue
                try:
                    owner = int(name.split("-", 1)[1].split(".", 1)[0])
                except ValueError:
                    continue
                if owner >= world:
                    os.remove(os.path.join(d, name))
        self._barrier()  # nobody writes until the workdir is clean
        shard_path = os.path.join(d, f"shard-{pid}.bin")
        tmp = shard_path + ".tmp"
        index_entries = []  # byte index: restore seeks straight to records
        offsets_ok = True   # stream must support tell() for a valid index
        with create_stream(tmp, "w") as s:
            ser.write_u32(s, _FORMAT_VERSION)
            ser.write_u64(s, len(leaves))
            for key, leaf in leaves:
                ser.write_str(s, key)
                shards = self._addressable_shards(leaf)
                ser.write_u64(s, len(shards))
                for index, data in shards:
                    # the shard's placement: (start, stop) per dim
                    ser.write_u8(s, len(index))
                    for (start, stop) in index:
                        ser.write_u64(s, start)
                        ser.write_u64(s, stop)
                    off = s.tell() if hasattr(s, "tell") else None
                    ser.write_ndarray(s, data)
                    if off is not None:
                        index_entries.append({
                            "key": key,
                            "placement": [list(p) for p in index],
                            "offset": off,
                            "nbytes": s.tell() - off,
                        })
                    else:
                        offsets_ok = False
        idx_path = os.path.join(d, f"shard-{pid}.idx.json")
        # publish order keeps every crash window restorable: drop any
        # stale index first (restore falls back to scanning the .bin),
        # then the .bin, then the new index — each via atomic replace.
        # The recorded bin_size lets restore reject an index that does
        # not match its .bin (e.g. torn re-save of an existing step).
        if os.path.exists(idx_path):
            os.remove(idx_path)
        os.replace(tmp, shard_path)
        if offsets_ok:  # a partial index would HIDE records; scan instead
            with create_stream(idx_path + ".tmp", "w") as s:
                json_dump({"version": _FORMAT_VERSION,
                           "entries": index_entries,
                           "bin_size": os.path.getsize(shard_path)}, s)
            os.replace(idx_path + ".tmp", idx_path)
        if pid == 0:
            meta = {
                "version": _FORMAT_VERSION,
                "step": step,
                "num_processes": jax.process_count(),
                "leaves": [
                    {"key": k,
                     "shape": list(np.shape(leaf)),
                     "dtype": np.dtype(
                         getattr(leaf, "dtype",
                                 np.asarray(leaf).dtype)).str}
                    for k, leaf in leaves],
                "user": metadata or {},
            }
            try:
                # elastic gangs stamp WHO wrote this step (gang,
                # member, rank, membership epoch, world): a restore
                # after an N→M reshard reads the stamp and re-derives
                # shard ownership from the same pure contract
                # (rendezvous/elastic.py) instead of assuming the
                # world never changed
                from dmlc_tpu.rendezvous.elastic import gang_metadata
                stamp = gang_metadata()
                if stamp is not None:
                    meta["rendezvous"] = stamp
            except Exception:  # noqa: BLE001 — the stamp is
                pass           # additive; saves never fail for it
            with create_stream(os.path.join(d, "meta.json"), "w") as s:
                json_dump(meta, s)
        self._barrier()           # all shard files durable
        if pid == 0:
            open(os.path.join(d, "COMMIT"), "wb").close()
            self._swap_in(final)
        self._barrier()           # COMMIT visible before any rank returns
        return final

    @staticmethod
    def _swap_in(final: str) -> None:
        """Make a fully committed ``final + ".new"`` become ``final``.

        Any old committed data leaves only AFTER its replacement is
        committed: a crash between the renames leaves a committed .new,
        which ``_resolve_step_dir`` serves and the NEXT ``save`` finishes
        swapping before it reuses .new. An orphaned .trash (crash after
        the second rename) is swept by the next swap.
        """
        import shutil
        d = final + ".new"
        trash = final + ".trash"
        if os.path.isdir(trash):
            shutil.rmtree(trash)
        if os.path.isdir(final):
            os.rename(final, trash)
        os.rename(d, final)
        if os.path.isdir(trash):
            shutil.rmtree(trash)

    @staticmethod
    def _addressable_shards(leaf: Any):
        """[(placement, shard_data)] for this process, where placement is
        ((start, stop), ...) per dim in the global array.

        Only replica 0 of each datum is written (standard dedup): a fully
        replicated leaf costs one copy per checkpoint, not one per
        device. Replica-0 shards tile the global array exactly, so
        restore can rebuild it from placements alone — independent of
        mesh topology, which makes restoring to a different device count
        or sharding legal.
        """
        import jax
        if not isinstance(leaf, jax.Array):
            arr = np.asarray(leaf)
            placement = tuple((0, s) for s in arr.shape)
            return ([(placement, arr)] if jax.process_index() == 0 else [])
        shape = leaf.shape
        out = []
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue
            placement = []
            for dim, sl in enumerate(shard.index):
                start = sl.start if sl.start is not None else 0
                stop = sl.stop if sl.stop is not None else shape[dim]
                placement.append((start, stop))
            out.append((tuple(placement), np.asarray(shard.data)))
        return out

    @staticmethod
    def _barrier() -> None:
        import jax
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("dmlc_tpu_ckpt")

    # -- restore

    @_spanned("checkpoint.restore")
    def restore(self, step: Optional[int] = None, like: Any = None,
                sharding_tree: Any = None) -> Tuple[Any, Dict[str, Any]]:
        """Load (tree, user_metadata). ``like`` supplies structure (and
        shardings, when its leaves are jax.Arrays); ``sharding_tree``
        overrides shardings explicitly.

        Sharded leaves are restored shard-locally: only the stored
        records whose placements intersect this process's addressable
        device slices are read (seek via the shard-*.idx.json byte
        index), and the global array is built with
        jax.make_array_from_single_device_arrays — no full-array host
        materialization. Unsharded leaves (or ``like=None``) fall back
        to full assembly. ``last_restore_bytes_read`` records the data
        bytes actually read from shard files.

        Restore also sweeps the replay page-cache spill dir
        (best-effort): a restore marks a resume boundary, and spill
        files written against inputs that have since changed must not
        be adoptable by the resumed run — the steady-replay mutation
        contract says replay re-earns from a clean re-parse after any
        source change. Only files whose recorded fingerprint fails a
        re-stat (plus crashed writers' orphaned .tmp files) are
        deleted; caches of unchanged sources are untouched."""
        import jax
        try:
            from dmlc_tpu.data.row_iter import sweep_stale_spill
            sweep_stale_spill()
        except Exception:  # noqa: BLE001 — hygiene must not block restore
            pass
        if step is None:
            step = self.latest_step()
            check(step is not None, f"no committed checkpoint under {self.root}")
        self.last_restore_bytes_read = 0
        self.last_restore_local_bytes = 0
        self.last_restore_peer_bytes = 0
        self.last_restore_wire_bytes = 0
        if self._remote:
            sd = self._step_key(step)
            check(self._remote_committed(sd),
                  f"checkpoint step {step} is not committed")
            meta = json_load(MemoryStream(
                self._get_object(f"{sd}/meta.json")))
            index = self._load_index_remote(sd)
            # the fanout cut: wire-fetch the digests THIS rank owns at
            # the CURRENT world into the page store, so peers can take
            # them from our /pages tier instead of the wire
            self._prefetch_owned_pages(index)
        else:
            d = self._resolve_step_dir(step)
            check(os.path.exists(os.path.join(d, "COMMIT")),
                  f"checkpoint step {step} is not committed")
            with create_stream(os.path.join(d, "meta.json"), "r") as s:
                meta = json_load(s)
            index = self._load_index(d)
        meta_shapes = {l["key"]: tuple(l["shape"])
                       for l in meta.get("leaves", [])}
        meta_dtypes = {l["key"]: np.dtype(l["dtype"])
                       for l in meta.get("leaves", [])}
        if like is None:
            host = self._assemble_full(index, meta_shapes, meta_dtypes)
            return host, meta.get("user", {})
        leaves, treedef = _flatten(like)
        shardings = None
        if sharding_tree is not None:
            sleaves, _ = _flatten(sharding_tree)
            shardings = dict(sleaves)

        def _target_sharding(key, proto):
            if shardings is not None:
                return shardings.get(key)
            if isinstance(proto, jax.Array) and hasattr(proto, "sharding"):
                return proto.sharding
            return None

        shard_restorable = {
            key for key, proto in leaves
            if _target_sharding(key, proto) is not None
            and key in index and key in meta_shapes}
        full_keys = [key for key, _ in leaves if key not in shard_restorable]
        full_cache = (self._assemble_full(index, meta_shapes, meta_dtypes,
                                          keys=full_keys)
                      if full_keys else {})
        new_leaves = []
        for key, proto in leaves:
            sharding = _target_sharding(key, proto)
            if key in shard_restorable:
                new_leaves.append(self._restore_sharded(
                    index, key, meta_shapes[key], meta_dtypes[key],
                    sharding))
                continue
            check(key in full_cache, f"checkpoint missing leaf {key!r}")
            full = full_cache[key]
            new_leaves.append(full if sharding is None
                              else jax.device_put(full, sharding))
        return jax.tree_util.tree_unflatten(treedef, new_leaves), \
            meta.get("user", {})

    # -- restore internals

    def _load_index(self, d: str) -> Dict[str, List[dict]]:
        """key -> [{file, placement, offset, nbytes}] covering EVERY
        shard-*.bin in the step dir: from its .idx.json when present and
        matching the .bin's size, else by a structural scan of the .bin
        (headers read, payloads seeked over — no data loaded). Mixed
        indexed/unindexed checkpoints (version skew, lost index) and
        stale indexes from a torn re-save are therefore restorable."""
        out: Dict[str, List[dict]] = {}
        for name in sorted(os.listdir(d)):
            if not (name.startswith("shard-") and name.endswith(".bin")):
                continue
            bin_path = os.path.join(d, name)
            idx_path = bin_path[:-len(".bin")] + ".idx.json"
            entries = None
            if os.path.exists(idx_path):
                with create_stream(idx_path, "r") as s:
                    idx = json_load(s)
                if (idx.get("bin_size") == os.path.getsize(bin_path)
                        and idx.get("version", _FORMAT_VERSION)
                        == _FORMAT_VERSION):
                    entries = [{
                        "file": bin_path,
                        "key": e["key"],
                        "placement": tuple(tuple(p)
                                           for p in e["placement"]),
                        "offset": int(e["offset"]),
                        "nbytes": int(e["nbytes"]),
                    } for e in idx.get("entries", [])]
            if entries is None:
                entries = self._scan_bin(bin_path)
            for e in entries:
                out.setdefault(e["key"], []).append(e)
        return out

    @staticmethod
    def _scan_bin(bin_path: str) -> List[dict]:
        """Build index entries by walking a shard file's structure,
        seeking past payloads (reads headers only)."""
        entries: List[dict] = []
        with create_stream(bin_path, "r") as s:
            version = ser.read_u32(s)
            check_eq(version, _FORMAT_VERSION, "shard version mismatch")
            nleaf = ser.read_u64(s)
            for _ in range(nleaf):
                key = ser.read_str(s)
                nsh = ser.read_u64(s)
                for _ in range(nsh):
                    ndim = ser.read_u8(s)
                    placement = tuple(
                        (ser.read_u64(s), ser.read_u64(s))
                        for _ in range(ndim))
                    off = s.tell()
                    dtype = np.dtype(ser.read_str(s))
                    adim = ser.read_u8(s)
                    shape = tuple(ser.read_u64(s) for _ in range(adim))
                    count = int(np.prod(shape)) if adim else 1
                    s.seek(s.tell() + dtype.itemsize * count)
                    entries.append({"file": bin_path, "key": key,
                                    "placement": placement,
                                    "offset": off,
                                    "nbytes": s.tell() - off})
        return entries

    def _read_entry(self, streams: Dict[str, Any], entry: dict,
                    cache: Optional[Dict[tuple, np.ndarray]] = None
                    ) -> np.ndarray:
        loc = (entry.get("file"), entry.get("offset", entry.get("digest")))
        if cache is not None and loc in cache:
            return cache[loc]
        if "digest" in entry:
            data = self._read_page_record(entry)
        else:
            s = streams.get(entry["file"])
            if s is None:
                s = streams[entry["file"]] = create_stream(
                    entry["file"], "r")
            s.seek(entry["offset"])
            self.last_restore_bytes_read += entry["nbytes"]
            _ckpt_count("restore_bytes", entry["nbytes"])
            data = ser.read_ndarray(s)
        if cache is not None:
            cache[loc] = data
        return data

    def _restore_sharded(self, index: Dict[str, List[dict]],
                         key: str, shape: tuple, dtype,
                         sharding) -> Any:
        """Build one global jax.Array reading only placements that
        intersect this process's addressable device slices."""
        import jax
        dev_map = sharding.addressable_devices_indices_map(tuple(shape))
        streams: Dict[str, Any] = {}
        slice_cache: Dict[tuple, np.ndarray] = {}  # device slice spans
        # records read once per restore even when several device spans
        # intersect the same stored record (replicated-saved leaf onto a
        # sharded target); dropped when this leaf completes
        read_cache: Dict[tuple, np.ndarray] = {}
        try:
            arrays = []
            for dev, idx_slices in dev_map.items():
                spans = tuple(
                    (sl.start if sl.start is not None else 0,
                     sl.stop if sl.stop is not None else shape[dim])
                    for dim, sl in enumerate(idx_slices))
                if spans in slice_cache:
                    local = slice_cache[spans]
                else:
                    local = np.empty(
                        tuple(stop - start for start, stop in spans), dtype)
                    filled = 0
                    for entry in index.get(key, []):
                        inter = _intersect(spans, entry["placement"])
                        if inter is None:
                            continue
                        data = self._read_entry(streams, entry, read_cache)
                        dst = tuple(
                            slice(lo - start, hi - start)
                            for (lo, hi), (start, _) in zip(inter, spans))
                        src = tuple(
                            slice(lo - pstart, hi - pstart)
                            for (lo, hi), (pstart, _) in zip(
                                inter, entry["placement"]))
                        local[dst] = data[src]
                        filled += local[dst].size
                    check_eq(filled, local.size,
                             f"leaf {key!r}: stored shards do not cover "
                             f"this process's slice")
                    slice_cache[spans] = local
                arrays.append(jax.device_put(local, dev))
            return jax.make_array_from_single_device_arrays(
                tuple(shape), sharding, arrays)
        finally:
            for s in streams.values():
                s.close()

    def _assemble_full(self, index: Dict[str, List[dict]],
                       meta_shapes: Dict[str, tuple],
                       meta_dtypes: Dict[str, Any],
                       keys: Optional[List[str]] = None
                       ) -> Dict[str, np.ndarray]:
        """Full host assembly of ``keys`` (default: every key) — the
        like=None / unsharded-leaf path. Reads only the listed keys'
        records, so one scalar in a tree of sharded leaves does not pull
        the whole model to host."""
        shards: Dict[str, List[tuple]] = {}
        streams: Dict[str, Any] = {}
        try:
            for key, entries in index.items():
                if keys is not None and key not in keys:
                    continue
                for entry in entries:
                    shards.setdefault(key, []).append(
                        (entry["placement"],
                         self._read_entry(streams, entry)))
        finally:
            for s in streams.values():
                s.close()
        return {key: self._reassemble(key, parts, meta_shapes.get(key),
                                      meta_dtypes.get(key))
                for key, parts in shards.items()}

    @staticmethod
    def _reassemble(key: str, parts: List[tuple],
                    full_shape, dtype) -> np.ndarray:
        """Rebuild the full host array from replica-0 shard placements."""
        if full_shape is None:
            full_shape = tuple(max(stop for (_, stop) in
                                   (pl[d] for pl, _ in parts))
                               for d in range(len(parts[0][0])))
        if dtype is None:
            dtype = parts[0][1].dtype
        out = np.empty(tuple(full_shape), dtype)
        covered = 0
        for placement, data in parts:
            slices = tuple(slice(start, stop) for (start, stop) in placement)
            out[slices] = data.reshape(out[slices].shape)
            covered += data.size
        if covered < out.size:
            raise DMLCError(
                f"checkpoint leaf {key!r}: shards cover {covered} of "
                f"{out.size} elements (missing shard files?)")
        return out

    # -------------------------------------- remote (obj://) plane

    def _step_key(self, step: int) -> str:
        return f"step-{step:08d}"

    def _key(self, rel: str) -> str:
        return f"{self._obj_prefix}/{rel}" if self._obj_prefix else rel

    def _client(self):
        from dmlc_tpu.io.objstore.fs import client
        c = client()
        check(c is not None,
              f"checkpoint root {self.root!r}: no object store "
              "configured (DMLC_TPU_OBJSTORE_ROOT / _ENDPOINT, or "
              "dmlc_tpu.io.objstore.configure)")
        return c

    @staticmethod
    def _pages_store():
        try:
            from dmlc_tpu.io.pagestore import PageStore
            return PageStore.default()
        except Exception:  # noqa: BLE001 — cache trouble != checkpoint failure
            return None

    @staticmethod
    def _record_digest(arr: np.ndarray) -> str:
        """Content address of one shard record: dtype + shape + bytes.
        The digest, not the (step, writer) coordinates, names the page
        object — an unchanged shard hashes to the SAME object across
        saves (incremental reuse) and across writers (replicated
        leaves dedup gang-wide)."""
        h = hashlib.sha256()
        h.update(arr.dtype.str.encode())
        h.update(repr(tuple(arr.shape)).encode())
        h.update(np.ascontiguousarray(arr))
        return h.hexdigest()[:32]

    @staticmethod
    def _serialize_record(arr: np.ndarray) -> bytes:
        buf = MemoryStream()
        ser.write_ndarray(buf, arr)
        return buf.getvalue()

    def _get_object(self, rel: str,
                    expected_len: Optional[int] = None) -> bytes:
        """One whole-object GET under the ``io.objstore.get`` seam
        (chaos injects here; a short payload retries under policy)."""
        from dmlc_tpu.resilience import inject as _inject
        c = self._client()
        key = self._key(rel)

        def attempt():
            data = _inject.corrupt(
                "io.objstore.get", c.get(self._bucket, key, 0, None))
            if expected_len is not None and len(data) != expected_len:
                raise IOError(
                    f"objstore: short GET on {self.root}/{rel}: got "
                    f"{len(data)}/{expected_len} bytes")
            return data

        data = guarded("io.objstore.get", attempt)
        _obj_count("get")
        _obj_count("bytes", len(data))
        _obj_count("bytes_served", len(data))
        return data

    def _remote_committed(self, sd: str) -> bool:
        c = self._client()
        try:
            guarded("io.objstore.stat",
                    lambda: c.head(self._bucket,
                                   self._key(f"{sd}/COMMIT")))
        except FileNotFoundError:
            return False
        _obj_count("stat")
        return True

    def _committed_steps_remote(self) -> List[int]:
        c = self._client()
        infos = guarded("io.objstore.list",
                        lambda: c.list(self._bucket, self._obj_prefix))
        _obj_count("list")
        pat = re.compile(r"step-(\d+)/COMMIT$")
        steps = {int(m.group(1)) for o in infos
                 for m in [pat.search(o.key)] if m}
        return sorted(steps)

    # -- remote save

    def _save_remote(self, step: int, tree: Any,
                     metadata: Optional[Dict[str, Any]],
                     writer: Optional[int],
                     num_writers: Optional[int]) -> str:
        """Device-direct save: each shard record streams straight to
        ``<root>/pages/<digest>.pg`` through the objstore write plane
        (multipart past ``put_part_bytes``) — no whole-tree host
        staging, and digests already present (this or any earlier
        save, locally committed or HEAD-probed) upload NOTHING."""
        import jax
        if writer is None:
            writer = jax.process_index()
        if num_writers is None:
            num_writers = jax.process_count()
        check(0 <= writer < num_writers,
              f"checkpoint: writer {writer} outside num_writers "
              f"{num_writers}")
        c = self._client()
        sd = self._step_key(step)
        if writer == 0 and hasattr(c, "delete"):
            # re-save of an existing step: it must not look committed
            # while its indexes are being rebuilt
            try:
                c.delete(self._bucket, self._key(f"{sd}/COMMIT"))
            except Exception:  # noqa: BLE001 — probe is best-effort
                pass
        leaves, _ = _flatten(tree)
        store = self._pages_store()
        written = reused = 0
        entries = []
        for key, leaf in leaves:
            for placement, data in self._addressable_shards(leaf):
                arr = np.ascontiguousarray(data)
                digest = self._record_digest(arr)
                nbytes = self._reusable_nbytes(c, store, digest)
                if nbytes is None:
                    payload = self._serialize_record(arr)
                    nbytes = len(payload)
                    with create_stream(
                            f"{self.root}/pages/{digest}.pg", "w") as s:
                        s.write(payload)
                    written += nbytes
                    self._commit_local_page(store, digest, payload)
                else:
                    reused += nbytes
                entries.append(
                    {"key": key,
                     "placement": [list(p) for p in placement],
                     "digest": digest, "nbytes": nbytes})
        with create_stream(
                f"{self.root}/{sd}/shard-{writer}.idx.json", "w") as s:
            json_dump({"version": _FORMAT_VERSION, "writer": writer,
                       "entries": entries}, s)
        self.last_save_bytes_written = written
        self.last_save_bytes_reused = reused
        _ckpt_count("save_bytes", written)
        if writer == 0:
            self._commit_remote(c, sd, step, leaves, metadata,
                                num_writers)
        return f"{self.root}/{sd}"

    def _reusable_nbytes(self, c, store, digest: str) -> Optional[int]:
        """Payload size when ``pages/<digest>.pg`` already exists —
        the incremental-save dedup. A locally committed page stamped
        with THIS root answers without any wire op; otherwise a HEAD
        probe (latency-only) asks the store. None = upload needed."""
        name = _PAGE_PREFIX + digest + ".pages"
        if store is not None and store.exists(name):
            stamp = store.stamp(name)
            if (stamp and stamp.get("digest") == digest
                    and stamp.get("root") == self.root
                    and "nbytes" in stamp):
                return int(stamp["nbytes"])
        try:
            info = guarded(
                "io.objstore.stat",
                lambda: c.head(self._bucket,
                               self._key(f"pages/{digest}.pg")))
        except FileNotFoundError:
            return None
        _obj_count("stat")
        return int(info.size)

    def _commit_local_page(self, store, digest: str,
                           payload: bytes) -> None:
        """Best-effort page-store commit of a page this process just
        moved (saved or fetched): the sidecar-stamped entry is what
        the gang ``/pages`` tier serves to peers, and what the next
        incremental save recognizes without a wire op. fingerprint
        None = content-addressed, immortal to the stale sweep."""
        if store is None:
            return
        from dmlc_tpu.io.codec import encode_page, tag
        try:
            store.commit_bytes(
                _PAGE_PREFIX + digest + ".pages",
                encode_page(payload, 0), fingerprint=None,
                meta={"digest": digest, "nbytes": len(payload),
                      "codec": tag(0), "root": self.root})
        except Exception:  # noqa: BLE001 — cache trouble != I/O failure
            pass

    def _commit_remote(self, c, sd: str, step: int, leaves,
                       metadata: Optional[Dict[str, Any]],
                       num_writers: int) -> None:
        """Writer 0's commit: meta.json, then wait for every writer's
        index (the remote analogue of the local save's barrier), then
        the COMMIT marker — a torn or in-flight save never lists as a
        committed step."""
        meta = {
            "version": _FORMAT_VERSION,
            "step": step,
            "num_processes": num_writers,
            "leaves": [
                {"key": k,
                 "shape": list(np.shape(leaf)),
                 "dtype": np.dtype(
                     getattr(leaf, "dtype",
                             np.asarray(leaf).dtype)).str}
                for k, leaf in leaves],
            "user": metadata or {},
        }
        try:
            from dmlc_tpu.rendezvous.elastic import gang_metadata
            stamp = gang_metadata()
            if stamp is not None:
                meta["rendezvous"] = stamp
        except Exception:  # noqa: BLE001 — the stamp is additive
            pass
        with create_stream(f"{self.root}/{sd}/meta.json", "w") as s:
            json_dump(meta, s)
        pat = re.compile(r"shard-(\d+)\.idx\.json$")
        deadline = time.monotonic() + 120.0
        while True:
            infos = guarded("io.objstore.list",
                            lambda: c.list(self._bucket, self._key(sd)))
            _obj_count("list")
            have = {int(m.group(1)) for o in infos
                    for m in [pat.search(o.key)] if m}
            if len(have & set(range(num_writers))) == num_writers:
                break
            check(time.monotonic() < deadline,
                  f"checkpoint step {step}: waited 120s for "
                  f"{num_writers} shard indexes, have {sorted(have)}")
            time.sleep(0.05)
        with create_stream(f"{self.root}/{sd}/COMMIT", "w") as s:
            s.write(b"")

    # -- remote restore

    def prefetch(self, step: Optional[int] = None) -> None:
        """Warm this rank's fanout cut ahead of ``restore()``:
        wire-fetch the pages ``content_owner`` assigns to this rank
        into the local page store, so gang peers can take them from
        our ``/pages`` tier. Remote roots only; a no-op without a
        peer tier. A restoring gang that barriers between
        ``prefetch()`` and ``restore()`` guarantees every page is
        staged at its owner before anyone assembles — no rank races
        ahead and pays wire for a page its peer has not fetched yet.
        The prefetched pages still report as "wire" (once) in the
        restore split: the wire cost was paid, just earlier."""
        check(self._remote,
              "checkpoint.prefetch applies to remote (obj://) roots")
        if step is None:
            step = self.latest_step()
            check(step is not None,
                  f"no committed checkpoint under {self.root}")
        sd = self._step_key(step)
        check(self._remote_committed(sd),
              f"checkpoint step {step} is not committed")
        self._prefetch_owned_pages(self._load_index_remote(sd))

    def _load_index_remote(self, sd: str) -> Dict[str, List[dict]]:
        c = self._client()
        infos = guarded("io.objstore.list",
                        lambda: c.list(self._bucket, self._key(sd)))
        _obj_count("list")
        pat = re.compile(r"shard-\d+\.idx\.json$")
        out: Dict[str, List[dict]] = {}
        for o in infos:
            if not pat.search(o.key):
                continue
            rel = (o.key[len(self._obj_prefix):].lstrip("/")
                   if self._obj_prefix else o.key)
            idx = json_load(MemoryStream(
                self._get_object(rel, expected_len=o.size)))
            check(idx.get("version", _FORMAT_VERSION) == _FORMAT_VERSION,
                  "checkpoint shard index version mismatch")
            for e in idx.get("entries", []):
                out.setdefault(e["key"], []).append({
                    "key": e["key"],
                    "placement": tuple(tuple(p)
                                       for p in e["placement"]),
                    "digest": e["digest"],
                    "nbytes": int(e["nbytes"]),
                })
        return out

    @staticmethod
    def _tier():
        try:
            from dmlc_tpu.io.objstore import peer as _peer_mod
            t = _peer_mod.tier()
        except Exception:  # noqa: BLE001 — no tier = no fanout, not an error
            return None
        if t is None or t.self_index is None or t.world <= 1:
            return None
        return t

    def _prefetch_owned_pages(self, index: Dict[str, List[dict]]) -> None:
        """The fanout cut: of all the checkpoint's digests, wire-fetch
        (and page-commit) the ones ``content_owner`` assigns to THIS
        rank at the CURRENT world — any world, including one different
        from the saving gang's. Peers then take these pages from our
        ``/pages`` tier, so each of M restoring ranks pays ~1/M of the
        wire. Best-effort: a failed prefetch leaves the page to the
        assembly pass's peer-then-wire ladder."""
        # preserve marks from an explicit prefetch(): those pages'
        # wire cost is still unreported, and the first store-read
        # must say "wire", not "local"
        self._prefetched = getattr(self, "_prefetched", None) or set()
        t = self._tier()
        if t is None:
            return
        from dmlc_tpu.rendezvous.elastic import content_owner
        store = self._pages_store()
        digests: Dict[str, int] = {}
        for entries in index.values():
            for e in entries:
                digests[e["digest"]] = e["nbytes"]
        for digest in sorted(digests):
            if content_owner(digest, t.world) != t.self_index:
                continue
            name = _PAGE_PREFIX + digest + ".pages"
            if store is not None and store.exists(name):
                continue
            try:
                payload = self._wire_page(digest, digests[digest])
            except Exception:  # noqa: BLE001 — assembly retries
                continue
            self._commit_local_page(store, digest, payload)
            self._prefetched.add(digest)

    def _read_page_record(self, entry: dict) -> np.ndarray:
        digest, nbytes = entry["digest"], entry["nbytes"]
        payload, src = self._page_payload(digest, nbytes)
        arr = ser.read_ndarray(MemoryStream(payload))
        if self._record_digest(arr) != digest:
            raise DMLCError(
                f"checkpoint page {digest}: content mismatch "
                "(corrupt page object)")
        self.last_restore_bytes_read += nbytes
        _ckpt_count("restore_bytes", nbytes)
        _ckpt_count(f"restore.{src}_bytes", nbytes)
        attr = f"last_restore_{src}_bytes"
        setattr(self, attr, getattr(self, attr) + nbytes)
        return arr

    def _page_payload(self, digest: str,
                      nbytes: int) -> Tuple[bytes, str]:
        """One content-addressed page, tiered: local page store →
        singleflight → peer owner's /pages → wire. Returns (payload,
        source) with source in {"local", "peer", "wire"} — a page this
        rank itself prefetched over the wire reports as "wire" once
        (the honest split), then "local"."""
        from dmlc_tpu.io.objstore.fs import _SINGLEFLIGHT, _count_sf
        name = _PAGE_PREFIX + digest + ".pages"
        store = self._pages_store()
        payload = self._local_page(store, name, nbytes)
        if payload is not None:
            if digest in getattr(self, "_prefetched", ()):
                self._prefetched.discard(digest)
                return payload, "wire"
            return payload, "local"
        key = (_PAGE_PREFIX, digest)
        if _SINGLEFLIGHT.lead(key):
            _count_sf("lead")
            try:
                return self._peer_or_wire_page(store, name, digest,
                                               nbytes)
            finally:
                _SINGLEFLIGHT.done(key)
        _count_sf("dedup")
        payload = self._local_page(store, name, nbytes)
        if payload is not None:
            return payload, "local"
        return self._peer_or_wire_page(store, name, digest, nbytes)

    def _local_page(self, store, name: str,
                    nbytes: int) -> Optional[bytes]:
        if store is None:
            return None
        from dmlc_tpu.io.codec import decode_page
        s = store.open_read(name)
        if s is None:
            return None
        with s:
            data = s.read_all()
        try:
            data = decode_page(data)
        except DMLCError:
            data = b""  # corrupt frame: treat as torn below
        if len(data) != nbytes:
            store.delete(name)
            return None
        return data

    def _peer_or_wire_page(self, store, name: str, digest: str,
                           nbytes: int) -> Tuple[bytes, str]:
        t = self._tier()
        if t is not None:
            from dmlc_tpu.rendezvous.elastic import content_owner
            owner = content_owner(digest, t.world)
            if owner != t.self_index:
                data = t.fetch_entry(owner, name, None, nbytes)
                if data is not None:
                    self._commit_local_page(store, digest, data)
                    return data, "peer"
        payload = self._wire_page(digest, nbytes)
        self._commit_local_page(store, digest, payload)
        return payload, "wire"

    def _wire_page(self, digest: str, nbytes: int) -> bytes:
        return self._get_object(f"pages/{digest}.pg",
                                expected_len=nbytes)
