"""Checkpoint/resume: device-buffer round-trips over Streams.

Reference: the primitives in include/dmlc/io.h (Stream::Write/Read,
dmlc::Serializable) + serializer.h + JSON metadata — the reference ships
the mechanism, downstream (XGBoost SaveModel) composes it. Here the
composition is provided too, TPU-natively:

- ``save_pytree``/``load_pytree``: any pytree of arrays ↔ one Stream
  (single-host path; works with np and jax arrays).
- ``ShardedCheckpoint``: multi-host jax.Arrays — each process writes ONLY
  its addressable shards to its own stream (`ckpt-<step>/shard-<pid>.bin`
  + `meta.json`), and restore rebuilds global arrays via
  jax.make_array_from_single_device_arrays. No host gather, no cross-host
  traffic: the "checkpoints never touch (other hosts') DRAM" north star.
  Writes are atomic (tmp + rename) and committed by a marker file so a
  torn save is never restored.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dmlc_tpu.io.stream import create_stream
from dmlc_tpu.utils import serializer as ser
from dmlc_tpu.utils.json_util import json_dump, json_load
from dmlc_tpu.utils.logging import DMLCError, check, check_eq

__all__ = ["save_pytree", "load_pytree", "ShardedCheckpoint"]

_FORMAT_VERSION = 1


def _flatten(tree: Any) -> Tuple[List[Tuple[str, Any]], Any]:
    import jax
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path) or "<root>"
        out.append((key, leaf))
    return out, treedef


def save_pytree(tree: Any, uri: str) -> None:
    """Serialize a pytree of arrays to one stream (single-host path)."""
    leaves, _ = _flatten(tree)
    with create_stream(uri, "w") as s:
        ser.write_u32(s, _FORMAT_VERSION)
        ser.write_u64(s, len(leaves))
        for key, leaf in leaves:
            ser.write_str(s, key)
            ser.write_ndarray(s, np.asarray(leaf))


def load_pytree(uri: str, like: Optional[Any] = None) -> Any:
    """Load a checkpoint; returns {key: array}, or the structure of
    ``like`` when given (keys must match)."""
    with create_stream(uri, "r") as s:
        version = ser.read_u32(s)
        check_eq(version, _FORMAT_VERSION, "checkpoint version mismatch")
        n = ser.read_u64(s)
        flat: Dict[str, np.ndarray] = {}
        for _ in range(n):
            key = ser.read_str(s)
            flat[key] = ser.read_ndarray(s)
    if like is None:
        return flat
    import jax
    leaves, treedef = _flatten(like)
    missing = [k for k, _ in leaves if k not in flat]
    if missing:
        raise DMLCError(f"checkpoint missing keys {missing}")
    return jax.tree_util.tree_unflatten(
        treedef, [flat[k] for k, _ in leaves])


class ShardedCheckpoint:
    """Per-process shard streams for global jax.Arrays (multi-host).

    Layout: ``<root>/step-<N>/shard-<pid>.bin`` + ``meta.json`` (written
    by process 0) + ``COMMIT`` marker. Each shard file holds, per leaf,
    the process's addressable shards (device index in the global device
    list, shard numpy data).
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    # -- paths

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.root, f"step-{step:08d}")

    def latest_step(self) -> Optional[int]:
        steps = []
        for name in os.listdir(self.root):
            if name.startswith("step-") and os.path.exists(
                    os.path.join(self.root, name, "COMMIT")):
                steps.append(int(name.split("-", 1)[1]))
        return max(steps) if steps else None

    def all_steps(self) -> List[int]:
        return sorted(
            int(n.split("-", 1)[1]) for n in os.listdir(self.root)
            if n.startswith("step-") and
            os.path.exists(os.path.join(self.root, n, "COMMIT")))

    # -- save

    def save(self, step: int, tree: Any,
             metadata: Optional[Dict[str, Any]] = None) -> str:
        import jax
        pid = jax.process_index()
        leaves, _ = _flatten(tree)
        d = self._step_dir(step)
        os.makedirs(d, exist_ok=True)
        shard_path = os.path.join(d, f"shard-{pid}.bin")
        tmp = shard_path + ".tmp"
        with create_stream(tmp, "w") as s:
            ser.write_u32(s, _FORMAT_VERSION)
            ser.write_u64(s, len(leaves))
            for key, leaf in leaves:
                ser.write_str(s, key)
                shards = self._addressable_shards(leaf)
                ser.write_u64(s, len(shards))
                for index, data in shards:
                    # the shard's placement: (start, stop) per dim
                    ser.write_u8(s, len(index))
                    for (start, stop) in index:
                        ser.write_u64(s, start)
                        ser.write_u64(s, stop)
                    ser.write_ndarray(s, data)
        os.replace(tmp, shard_path)
        if pid == 0:
            meta = {
                "version": _FORMAT_VERSION,
                "step": step,
                "num_processes": jax.process_count(),
                "leaves": [
                    {"key": k,
                     "shape": list(np.shape(leaf)),
                     "dtype": np.dtype(
                         getattr(leaf, "dtype",
                                 np.asarray(leaf).dtype)).str}
                    for k, leaf in leaves],
                "user": metadata or {},
            }
            with create_stream(os.path.join(d, "meta.json"), "w") as s:
                json_dump(meta, s)
        self._barrier()           # all shard files durable
        if pid == 0:
            open(os.path.join(d, "COMMIT"), "wb").close()
        self._barrier()           # COMMIT visible before any rank returns
        return d

    @staticmethod
    def _addressable_shards(leaf: Any):
        """[(placement, shard_data)] for this process, where placement is
        ((start, stop), ...) per dim in the global array.

        Only replica 0 of each datum is written (standard dedup): a fully
        replicated leaf costs one copy per checkpoint, not one per
        device. Replica-0 shards tile the global array exactly, so
        restore can rebuild it from placements alone — independent of
        mesh topology, which makes restoring to a different device count
        or sharding legal.
        """
        import jax
        if not isinstance(leaf, jax.Array):
            arr = np.asarray(leaf)
            placement = tuple((0, s) for s in arr.shape)
            return ([(placement, arr)] if jax.process_index() == 0 else [])
        shape = leaf.shape
        out = []
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue
            placement = []
            for dim, sl in enumerate(shard.index):
                start = sl.start if sl.start is not None else 0
                stop = sl.stop if sl.stop is not None else shape[dim]
                placement.append((start, stop))
            out.append((tuple(placement), np.asarray(shard.data)))
        return out

    @staticmethod
    def _barrier() -> None:
        import jax
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("dmlc_tpu_ckpt")

    # -- restore

    def restore(self, step: Optional[int] = None, like: Any = None,
                sharding_tree: Any = None) -> Tuple[Any, Dict[str, Any]]:
        """Load (tree, user_metadata). ``like`` supplies structure (and
        shardings, when its leaves are jax.Arrays); ``sharding_tree``
        overrides shardings explicitly."""
        import jax
        if step is None:
            step = self.latest_step()
            check(step is not None, f"no committed checkpoint under {self.root}")
        d = self._step_dir(step)
        check(os.path.exists(os.path.join(d, "COMMIT")),
              f"checkpoint step {step} is not committed")
        with create_stream(os.path.join(d, "meta.json"), "r") as s:
            meta = json_load(s)
        # gather every key's shards: [(placement, data), ...]
        shards: Dict[str, List[tuple]] = {}
        for name in sorted(os.listdir(d)):
            if not name.startswith("shard-"):
                continue
            with create_stream(os.path.join(d, name), "r") as s:
                version = ser.read_u32(s)
                check_eq(version, _FORMAT_VERSION, "shard version mismatch")
                nleaf = ser.read_u64(s)
                for _ in range(nleaf):
                    key = ser.read_str(s)
                    nsh = ser.read_u64(s)
                    for _ in range(nsh):
                        ndim = ser.read_u8(s)
                        placement = tuple(
                            (ser.read_u64(s), ser.read_u64(s))
                            for _ in range(ndim))
                        data = ser.read_ndarray(s)
                        shards.setdefault(key, []).append((placement, data))
        meta_shapes = {l["key"]: tuple(l["shape"])
                       for l in meta.get("leaves", [])}
        meta_dtypes = {l["key"]: np.dtype(l["dtype"])
                       for l in meta.get("leaves", [])}
        host: Dict[str, np.ndarray] = {
            key: self._reassemble(key, parts, meta_shapes.get(key),
                                  meta_dtypes.get(key))
            for key, parts in shards.items()}
        if like is None:
            return host, meta.get("user", {})
        leaves, treedef = _flatten(like)
        shardings = None
        if sharding_tree is not None:
            sleaves, _ = _flatten(sharding_tree)
            shardings = dict(sleaves)
        new_leaves = []
        for key, proto in leaves:
            check(key in host, f"checkpoint missing leaf {key!r}")
            full = host[key]
            sharding = None
            if shardings is not None:
                sharding = shardings.get(key)
            elif isinstance(proto, jax.Array) and hasattr(proto, "sharding"):
                sharding = proto.sharding
            if sharding is None:
                new_leaves.append(full)
            else:
                # resharding-safe: device_put distributes the full host
                # array per the target sharding (local devices only get
                # their own slices)
                new_leaves.append(jax.device_put(full, sharding))
        return jax.tree_util.tree_unflatten(treedef, new_leaves), \
            meta.get("user", {})

    @staticmethod
    def _reassemble(key: str, parts: List[tuple],
                    full_shape, dtype) -> np.ndarray:
        """Rebuild the full host array from replica-0 shard placements."""
        if full_shape is None:
            full_shape = tuple(max(stop for (_, stop) in
                                   (pl[d] for pl, _ in parts))
                               for d in range(len(parts[0][0])))
        if dtype is None:
            dtype = parts[0][1].dtype
        out = np.empty(tuple(full_shape), dtype)
        covered = 0
        for placement, data in parts:
            slices = tuple(slice(start, stop) for (start, stop) in placement)
            out[slices] = data
            covered += data.size
        if covered < out.size:
            raise DMLCError(
                f"checkpoint leaf {key!r}: shards cover {covered} of "
                f"{out.size} elements (missing shard files?)")
        return out
