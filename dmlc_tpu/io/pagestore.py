"""ONE fingerprint-stamped on-disk page store for every cache tier.

Before this module the repo had three separately-invented on-disk cache
layers — DiskRowIter's binary row pages, RoundSpillWriter's round pages
(data/row_iter.py), and CachedInputSplit's chunk cache — each with its
own tmp+rename discipline, its own staleness story (fingerprint header,
sidecar meta, or a trust-forever ``.done`` marker), and no shared byte
budget. They now all route their on-disk bytes through :class:`PageStore`:

- **one commit protocol** — writes land in a tmp file and are published
  by an atomic ``os.replace`` under a resilience ``guarded()`` site, so
  a crashed or aborted build never masquerades as a complete cache;
- **one staleness stamp** — every committed entry carries a sidecar
  ``<entry>.meta.json`` recording the SOURCE fingerprint
  (``[[path, size, mtime_ns], ...]``, scheme-aware: remote ``obj://``
  sources stat through the FileSystem seam), and :meth:`PageStore.sweep`
  is the one sweep that removes entries whose sources changed, dead
  writers' files, and orphaned tmps/sidecars;
- **one byte budget** — committed bytes are accounted per store root and
  LRU-evicted (by entry mtime, bumped on every read) when a budget is
  set (``DMLC_TPU_PAGESTORE_BUDGET`` or :meth:`PageStore.set_budget`),
  skipping entries pinned by this process or owned by live writers;
- **one telemetry surface** — ``pagestore.hit`` / ``pagestore.miss`` /
  ``pagestore.evict`` counters (rendered ``dmlc_pagestore_*_total`` by
  obs/serve) so a remote epoch's hydration behavior is provable from
  /metrics alone.

The remote I/O plane (``dmlc_tpu.io.objstore``) hydrates ranged-GET
blocks into the same store, which is what makes a second epoch over an
``obj://`` URI wire-free: the blocks steady replay wants are already
local pages.
"""

from __future__ import annotations

import json
import os
import re
import stat as _stat_mod
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from dmlc_tpu.io.stream import Stream, create_stream
from dmlc_tpu.utils.logging import DMLCError, check

__all__ = [
    "PageStore", "PageWriter", "default_store_dir",
    "stat_uri", "stat_fingerprint", "fingerprint_fresh",
    "ENV_BUDGET", "ENV_STORE_DIR", "META_SUFFIX",
]

ENV_BUDGET = "DMLC_TPU_PAGESTORE_BUDGET"
ENV_STORE_DIR = "DMLC_TPU_PAGESTORE_DIR"
META_SUFFIX = ".meta.json"

_TMP_RE = re.compile(r"\.tmp(?:\.(\d+))?$")
# round-spill entries embed their writer pid in the NAME
# (rounds-<key>-p<pid>-<seq>.pages) — a dead owner's file can never be
# adopted and is reclaimed by sweep/eviction
_NAME_PID_RE = re.compile(r"-p(\d+)-\d+\.pages(\.tmp)?$")


def default_store_dir() -> str:
    """The shared default root: spill pages, derived caches, and
    hydrated remote blocks all land here unless a caller names a
    directory — one dir, one sweep, one budget.
    ``DMLC_TPU_PAGESTORE_DIR`` overrides it (read per call, so a gang
    worker sharing a host with its peers can give each rank its OWN
    store — what the objstore peer tier's ``/pages`` endpoint and the
    config-15 gang bench rely on)."""
    env = os.environ.get(ENV_STORE_DIR)
    if env:
        return env
    return os.path.join(tempfile.gettempdir(), "dmlc_tpu_spill")


def _pid_dead(pid: int) -> bool:
    """Liveness probe for a writer pid recorded on THIS host (store
    roots are host-local). Pid reuse can keep a dead file one sweep
    longer — bounded, accepted. The ONE liveness rule for every
    page/cache cleanup site."""
    if pid == os.getpid():
        return False
    try:
        os.kill(pid, 0)
        return False
    except ProcessLookupError:
        return True
    except OSError:
        return False  # alive but not ours (EPERM) — keep


def _name_pid(name: str) -> Optional[int]:
    m = _NAME_PID_RE.search(name)
    return int(m.group(1)) if m else None


def _name_owner_dead(name: str) -> Optional[bool]:
    """Liveness of a pid embedded in an entry name: True = dead,
    False = alive (or us), None = no pid in the name."""
    pid = _name_pid(name)
    return None if pid is None else _pid_dead(pid)


# ------------------------------------------------------- scheme-aware stat

def stat_uri(uri: str) -> Tuple[int, int, int, int]:
    """(size, mtime_ns, ctime_ns, inode) for a possibly scheme-bearing
    path — THE stat rule for fingerprints. Local and ``tpu://`` paths
    use os.stat (full richness); other registered schemes stat through
    their FileSystem (``get_path_info``), reporting 0 for the fields
    object stores do not have. Raises OSError for missing local files,
    FileNotFoundError/DMLCError from remote backends."""
    from dmlc_tpu.io.tpu_fs import local_path
    p = local_path(uri)
    if "://" not in p:
        st = os.stat(p)
        return (st.st_size, st.st_mtime_ns, st.st_ctime_ns, st.st_ino)
    from dmlc_tpu.io.filesys import URI, FileSystem
    u = URI(p)
    fs = FileSystem.get_instance(u)
    info = fs.get_path_info(u)
    return (info.size, info.mtime_ns, 0, 0)


def stat_fingerprint(paths) -> List[List[Any]]:
    """``[[path, size, mtime_ns], ...]`` — the sidecar stamp shape
    shared by every cache layer (and understood by :meth:`sweep`)."""
    out = []
    for p in paths:
        size, mtime_ns, _, _ = stat_uri(p)
        out.append([p, size, mtime_ns])
    return out


def fingerprint_fresh(fp) -> Optional[bool]:
    """Re-stat a recorded fingerprint: True = sources unchanged,
    False = changed/missing (stale), None = unknowable (e.g. the
    recording scheme has no backend configured in THIS process — never
    judge stale what we cannot stat)."""
    if not fp:
        return None
    for entry in fp:
        fpath, size, mtime_ns = entry[0], entry[1], entry[2]
        try:
            now_size, now_mtime, _, _ = stat_uri(fpath)
        except (OSError, ValueError):
            return False  # gone / unstatable locally: stale
        except DMLCError:
            return None  # scheme unconfigured here: unknowable
        if now_size != size or now_mtime != mtime_ns:
            return False
    return True


# ---------------------------------------------------------------- metrics

def _count(which: str, n: int = 1) -> None:
    try:
        from dmlc_tpu.obs.metrics import REGISTRY
        REGISTRY.counter(f"pagestore.{which}").inc(n)
    except Exception:  # noqa: BLE001 — telemetry must not break caching
        pass


# ------------------------------------------------------------ page writer

class PageWriter:
    """An in-flight page-store entry: write to ``.stream``, then
    :meth:`commit` (atomic publish + sidecar stamp + budget accounting)
    or :meth:`abort` (nothing left behind)."""

    def __init__(self, store: "PageStore", name: str,
                 fingerprint=None, meta: Optional[dict] = None,
                 commit_site: str = "pagestore.commit",
                 tmp_suffix: Optional[str] = None):
        self._store = store
        self.name = name
        self.path = store.path(name)
        self._fingerprint = fingerprint
        self._meta = dict(meta or {})
        self._site = commit_site
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        if tmp_suffix is None:
            tmp_suffix = f".tmp.{os.getpid()}"
            # reap dead predecessors' orphaned tmps for this entry:
            # each racing builder writes its own pid-named tmp, the
            # replaces are atomic, last complete build wins
            import glob
            for orphan in glob.glob(glob.escape(self.path) + ".tmp.*"):
                m = _TMP_RE.search(orphan)
                if m and m.group(1) and _pid_dead(int(m.group(1))):
                    try:
                        os.remove(orphan)
                    except OSError:
                        pass
        self.tmp = self.path + tmp_suffix
        self._s: Optional[Stream] = create_stream(self.tmp, "w")

    @property
    def stream(self) -> Stream:
        check(self._s is not None, "PageWriter already closed")
        return self._s

    def write(self, data) -> int:
        return self.stream.write(data)

    def commit(self) -> str:
        """Close, publish atomically under the commit site's retry
        policy, stamp the sidecar, account the bytes (evicting LRU
        entries if the store is over budget). Returns the entry path."""
        from dmlc_tpu.resilience.policy import guarded
        check(self._s is not None, "PageWriter already closed")
        self._s.close()
        self._s = None
        # the atomic publish rename is idempotent, so transient errors
        # (and injected chaos) retry under policy instead of abandoning
        # the freshly built pages
        guarded(self._site, lambda: os.replace(self.tmp, self.path))
        meta = dict(self._meta)
        meta["fingerprint"] = self._fingerprint
        try:
            meta["bytes"] = os.path.getsize(self.path)
            self._store._note_committed(meta["bytes"])
        except OSError:
            pass
        self._store._stamp_entry(self.name, meta)
        self._store.evict_to_budget()
        return self.path

    def abort(self) -> None:
        if self._s is not None:
            try:
                self._s.close()
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass
            self._s = None
        try:
            os.remove(self.tmp)
        except OSError:
            pass


# -------------------------------------------------------------- the store

class PageStore:
    """A directory of atomically-committed, fingerprint-stamped page
    files with byte-budget LRU accounting. One instance per root
    (:meth:`at` caches them); :meth:`default` is the shared spill-dir
    store every derived cache and hydrated remote block uses."""

    _by_root: Dict[str, "PageStore"] = {}
    _cls_lock = threading.Lock()
    # process-global pins, REFCOUNTED per path: two iterators serving
    # the same derived cache each pin it, and the survivor's pin holds
    # after the first one's __del__ unpins. Eviction and sweep skip
    # pinned entries; cross-process protection comes from LRU recency
    # + the pid-liveness rule.
    _pinned: Dict[str, int] = {}

    def __init__(self, root: str, byte_budget: Optional[int] = None):
        self.root = os.path.abspath(root)
        self.byte_budget = byte_budget
        self._lock = threading.Lock()
        # committed-bytes running total: None = unknown (rescan). Keeps
        # the per-commit budget check O(1) on the hot hydration path —
        # a full listdir+stat scan per committed block is O(N^2) over a
        # cold epoch. Another process's writes are invisible to the
        # cache until our next full scan; host-local heuristic,
        # accepted (eviction is delayed, never unsafe).
        self._used_cache: Optional[int] = None

    # -- construction

    @classmethod
    def at(cls, root: str,
           byte_budget: Optional[int] = None) -> "PageStore":
        key = os.path.abspath(root)
        with cls._cls_lock:
            store = cls._by_root.get(key)
            if store is None:
                store = cls(key, byte_budget)
                cls._by_root[key] = store
            elif byte_budget is not None:
                store.byte_budget = byte_budget
        return store

    @classmethod
    def default(cls) -> "PageStore":
        store = cls.at(default_store_dir())
        if store.byte_budget is None:
            env = os.environ.get(ENV_BUDGET)
            if env:
                try:
                    store.byte_budget = int(env)
                except ValueError:
                    pass
        return store

    @classmethod
    def for_path(cls, path: str) -> Tuple["PageStore", str]:
        """(store rooted at the path's directory, entry name) — how
        explicit cache paths (DiskRowIter, CachedInputSplit) join the
        unified store without moving their files."""
        path = os.path.abspath(path)
        return cls.at(os.path.dirname(path)), os.path.basename(path)

    @classmethod
    def known_roots(cls) -> List[str]:
        with cls._cls_lock:
            return list(cls._by_root)

    # -- paths / stamps

    def path(self, name: str) -> str:
        return os.path.join(self.root, name)

    def exists(self, name: str) -> bool:
        return os.path.exists(self.path(name))

    def stamp(self, name: str) -> Optional[dict]:
        """The committed sidecar meta, or None (no sidecar = a legacy
        or header-stamped entry; its staleness is judged elsewhere)."""
        try:
            with open(self.path(name) + META_SUFFIX) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _stamp_entry(self, name: str, meta: dict) -> None:
        tmp = self.path(name) + META_SUFFIX + f".tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, self.path(name) + META_SUFFIX)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass

    # -- write / read

    def writer(self, name: str, fingerprint=None,
               meta: Optional[dict] = None,
               commit_site: str = "pagestore.commit",
               tmp_suffix: Optional[str] = None) -> PageWriter:
        return PageWriter(self, name, fingerprint=fingerprint, meta=meta,
                          commit_site=commit_site, tmp_suffix=tmp_suffix)

    def commit_bytes(self, name: str, data: bytes, fingerprint=None,
                     meta: Optional[dict] = None) -> str:
        """One-shot write-and-commit of a fully materialized payload
        (the content-addressed checkpoint pages' shape:
        ``fingerprint=None`` entries are immortal to the stale sweep
        and served by the gang ``/pages`` tier as-is). Returns the
        entry path; aborts cleanly on failure."""
        w = self.writer(name, fingerprint=fingerprint, meta=meta)
        try:
            w.write(data)
        except Exception:
            w.abort()
            raise
        return w.commit()

    def lookup(self, name: str, fingerprint=None) -> Optional[str]:
        """Entry path when present and fresh, else None. Counts ONE
        hit or miss. With a ``fingerprint``, a committed stamp that
        does not match it marks the entry stale: it is deleted and the
        lookup is a miss (the caller re-earns the cache)."""
        p = self.path(name)
        if not os.path.exists(p):
            _count("miss")
            return None
        if fingerprint is not None:
            meta = self.stamp(name)
            if meta is not None and meta.get("fingerprint") is not None \
                    and meta["fingerprint"] != [list(e)
                                                for e in fingerprint]:
                self.delete(name)
                _count("miss")
                return None
        _count("hit")
        self.touch(name)
        return p

    def open_read(self, name: str) -> Optional[Stream]:
        """Seekable stream over a present entry (counts a hit and
        bumps its LRU clock), or None (counts a miss)."""
        p = self.path(name)
        try:
            s = create_stream(p, "r")
        except FileNotFoundError:
            _count("miss")
            return None
        _count("hit")
        self.touch(name)
        return s

    def touch(self, name: str) -> None:
        try:
            os.utime(self.path(name))
        except OSError:
            pass

    def delete(self, name: str) -> bool:
        """Remove an entry and its sidecar; True when the entry file
        existed. Drops every pin on the entry (a deleted path has
        nothing left to protect)."""
        p = self.path(name)
        with self._cls_lock:
            PageStore._pinned.pop(p, None)
        size = None
        try:
            size = os.path.getsize(p)
            os.remove(p)
            existed = True
        except OSError:
            existed = False
        if existed and size is not None and self._used_cache is not None:
            self._used_cache = max(0, self._used_cache - size)
        try:
            os.remove(p + META_SUFFIX)
        except OSError:
            pass
        return existed

    def _note_committed(self, nbytes: int) -> None:
        if self._used_cache is not None:
            self._used_cache += nbytes

    # -- pinning

    def pin(self, name: str) -> None:
        p = self.path(name)
        with self._cls_lock:
            PageStore._pinned[p] = PageStore._pinned.get(p, 0) + 1

    def unpin(self, name: str) -> None:
        p = self.path(name)
        with self._cls_lock:
            n = PageStore._pinned.get(p, 0) - 1
            if n > 0:
                PageStore._pinned[p] = n
            else:
                PageStore._pinned.pop(p, None)

    def _is_pinned(self, path: str) -> bool:
        with self._cls_lock:
            return PageStore._pinned.get(path, 0) > 0

    # -- accounting / eviction

    def _entries(self) -> List[Tuple[str, str, int, float]]:
        """Accountable entries: committed files the store recognizes —
        ``.pages`` suffix or a sidecar stamp. Alien files are never
        touched. Returns (name, path, size, mtime)."""
        try:
            names = set(os.listdir(self.root))
        except OSError:
            self._used_cache = 0  # no root yet: nothing committed
            return []
        out = []
        for name in sorted(names):
            if name.endswith(META_SUFFIX) or _TMP_RE.search(name):
                continue
            if not (name.endswith(".pages")
                    or name + META_SUFFIX in names):
                continue
            path = os.path.join(self.root, name)
            try:
                st = os.stat(path)
            except OSError:
                st = None  # vanished during listing: skip, not retry
            if st is None or not _stat_mod.S_ISREG(st.st_mode):
                continue
            out.append((name, path, st.st_size, st.st_mtime))
        self._used_cache = sum(size for _, _, size, _ in out)
        return out

    def used_bytes(self) -> int:
        return sum(size for _, _, size, _ in self._entries())

    def set_budget(self, byte_budget: Optional[int]) -> int:
        """Set (or clear) the store's byte budget and evict down to it.
        Returns entries evicted."""
        self.byte_budget = byte_budget
        return self.evict_to_budget()

    def evict_to_budget(self) -> int:
        """LRU-evict committed entries until used bytes fit the budget.
        Pinned entries and entries whose name embeds a LIVE writer pid
        are skipped — eviction reclaims cold caches, it does not pull
        pages out from under a serving iterator. The under-budget path
        is O(1) via the running committed-bytes total; only a
        possibly-over-budget store pays the full scan."""
        if self.byte_budget is None:
            return 0
        if self._used_cache is not None \
                and self._used_cache <= self.byte_budget:
            return 0
        with self._lock:
            entries = self._entries()
            used = sum(size for _, _, size, _ in entries)
            if used <= self.byte_budget:
                return 0
            evicted = 0
            # oldest mtime first — touch() on every read keeps live
            # entries at the warm end
            for name, path, size, _ in sorted(entries,
                                              key=lambda e: e[3]):
                if used <= self.byte_budget:
                    break
                if self._is_pinned(path):
                    continue
                if _name_owner_dead(name) is False:
                    continue  # a LIVE writer's spill file
                if self.delete(name):
                    used -= size
                    evicted += 1
                    _count("evict")
            return evicted

    # -- the one sweep

    def sweep(self, max_tmp_age_s: float = 600.0,
              header_meta: Optional[Callable[[str],
                                             Optional[dict]]] = None) -> int:
        """Remove stale-fingerprint entries, dead writers' files, and
        orphaned tmps/sidecars. Returns ENTRIES removed (an entry and
        its sidecar count once). ``header_meta(path)`` lets callers
        supply meta for entries that carry their stamp in a file header
        instead of a sidecar (the round-spill format)."""
        d = self.root
        if not os.path.isdir(d):
            return 0
        removed = 0
        now = time.time()
        names = set(os.listdir(d))
        for name in sorted(names):
            path = os.path.join(d, name)
            tmp_m = _TMP_RE.search(name)
            if tmp_m:
                # a live writer's tmp is NEVER deleted, however slow
                # the epoch; dead-owner tmps go now, anonymous ones by
                # age only
                if tmp_m.group(1):
                    dead = _pid_dead(int(tmp_m.group(1)))
                else:
                    dead = _name_owner_dead(name)
                try:
                    if dead or (dead is None
                                and now - os.path.getmtime(path)
                                > max_tmp_age_s):
                        os.remove(path)
                        removed += 1
                except OSError:
                    pass
                continue
            if name.endswith(META_SUFFIX):
                # sidecar without its entry (failed/crashed build):
                # nothing will ever pair with it — sweep it directly
                if name[:-len(META_SUFFIX)] not in names:
                    try:
                        os.remove(path)
                        removed += 1
                    except OSError:
                        pass
                continue
            if not (name.endswith(".pages")
                    or name + META_SUFFIX in names):
                continue  # never delete what we do not recognize
            if self._is_pinned(path):
                # a live iterator in THIS process is serving the entry:
                # even a stale-stamped one is skipped (the iterator's
                # own mutation detectors own that case); it is swept
                # once unpinned
                continue
            if _name_owner_dead(name):
                if self.delete(name):  # entry + sidecar, counted once
                    removed += 1
                continue
            meta = self.stamp(name)
            if meta is None and header_meta is not None:
                meta = header_meta(path)
            if meta is None:
                continue  # unknowable: never delete what we can't read
            fresh = fingerprint_fresh(meta.get("fingerprint"))
            if fresh is False:
                if self.delete(name):
                    removed += 1
        return removed
