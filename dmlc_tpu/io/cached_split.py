"""Write-through local chunk cache for remote splits.

Reference: src/io/cached_input_split.h — CachedInputSplit (URI suffix
``#cache.file``): first pass streams from the source while writing chunks
to a local cache file; later passes replay the cache (pure local reads).

Cache format: sequence of ``u64 length | chunk bytes``; the cache path is
suffixed with ``.pK-N`` so different (part, num_parts) shards never mix.

The on-disk discipline is the unified page store
(:mod:`dmlc_tpu.io.pagestore`): the first pass writes through a
:class:`~dmlc_tpu.io.pagestore.PageWriter` (pid-unique tmp, atomic
commit) and the committed entry is STAMPED with the source fingerprint
(``[[path, size, mtime_ns], ...]`` of the base split's files, stat'ed
through the FileSystem seam so remote ``obj://`` sources stamp too).
The pre-pagestore ``.done`` marker trusted the cache forever; now a
lookup against the current fingerprint detects a changed source and
RE-RUNS the first pass instead of replaying stale bytes, and the entry
participates in the one store sweep and byte budget.
"""

from __future__ import annotations

import struct
from typing import Iterator, Optional

from dmlc_tpu.io.input_split import InputSplit
from dmlc_tpu.io.pagestore import PageStore, stat_fingerprint
from dmlc_tpu.utils.logging import check

__all__ = ["CachedInputSplit"]


class CachedInputSplit(InputSplit):
    def __init__(self, base: InputSplit, cache_file: str):
        self._base = base
        self._cache_template = cache_file
        self._configure_paths()
        self._reader = None
        self._writer = None
        self._bytes = 0

    def _configure_paths(self) -> None:
        part = getattr(self._base, "part_index", 0)
        npart = getattr(self._base, "num_parts", 1)
        self._cache_path = f"{self._cache_template}.p{part}-{npart}"
        self._store, self._entry = PageStore.for_path(self._cache_path)

    def _fingerprint(self):
        """Current ``[[path, size, mtime_ns], ...]`` of the base
        split's backing files, or None when they cannot be stat'ed
        (the cache then trusts its existence — the fallback when a
        base split does not expose its file list)."""
        files = getattr(self._base, "_files", None)
        if not files:
            return None
        try:
            return stat_fingerprint(p for p, _ in files)
        except Exception:  # noqa: BLE001 — non-stat-able source
            return None

    def before_first(self) -> None:
        self._recbuf = None
        self._recpos = 0
        self._bytes = 0
        if self._writer is not None:
            # torn pass: discard partial cache
            self._writer.abort()
            self._writer = None
        if self._reader is not None:
            self._reader.close()
            self._reader = None
        # one lookup per pass: a committed entry whose stamp matches
        # the CURRENT source fingerprint replays; a stale stamp deletes
        # the entry (lookup counts the miss) and the first pass re-runs
        fp = self._fingerprint()
        cached = self._store.lookup(self._entry, fingerprint=fp)
        if cached is None:
            self._base.before_first()
            self._writer = self._store.writer(self._entry,
                                              fingerprint=fp)
        else:
            self._reader = self._store.open_read(self._entry)
            if self._reader is None:  # evicted between lookup and open
                self._base.before_first()
                self._writer = self._store.writer(self._entry,
                                                  fingerprint=fp)

    def next_chunk(self) -> Optional[bytes]:
        if self._reader is None and self._writer is None:
            self.before_first()
        if self._reader is not None:
            head = self._reader.read(8)
            if len(head) < 8:
                return None
            (n,) = struct.unpack("<Q", head)
            chunk = self._reader.read_exact(n) if n else b""
            check(len(chunk) == n, "cache file truncated")
            self._bytes += n
            return chunk
        chunk = self._base.next_chunk()
        if chunk is None:
            # atomic commit + fingerprint stamp (replaces the old
            # trust-forever .done marker)
            self._writer.commit()
            self._writer = None
            return None
        self._writer.write(struct.pack("<Q", len(chunk)))
        self._writer.write(chunk)
        self._bytes += len(chunk)
        return chunk

    def next_record(self) -> Optional[bytes]:
        while True:
            buf = getattr(self, "_recbuf", None)
            pos = getattr(self, "_recpos", 0)
            if buf is not None and pos < len(buf):
                self._recpos = pos + 1
                return buf[pos]
            chunk = self.next_chunk()
            if chunk is None:
                self._recbuf = None
                return None
            self._recbuf = list(self.extract_records(chunk))
            self._recpos = 0

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        return self._base.extract_records(chunk)

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        self._base.reset_partition(part_index, num_parts)
        if self._writer is not None:
            self._writer.abort()
            self._writer = None
        self._configure_paths()
        self._reader = None
        self.before_first()

    def get_total_size(self) -> int:
        return self._base.get_total_size()

    @property
    def bytes_read(self) -> int:
        return self._bytes
