"""Write-through local chunk cache for remote splits.

Reference: src/io/cached_input_split.h — CachedInputSplit (URI suffix
``#cache.file``): first pass streams from the source while writing chunks
to a local cache file; later passes replay the cache (pure local reads).

Cache format: sequence of ``u64 length | chunk bytes``; the cache path is
suffixed with ``.pK-N`` so different (part, num_parts) shards never mix.
A ``.done`` marker commits the cache (a torn first pass is re-run).
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, Optional

from dmlc_tpu.io.input_split import InputSplit
from dmlc_tpu.utils.logging import check

__all__ = ["CachedInputSplit"]


class CachedInputSplit(InputSplit):
    def __init__(self, base: InputSplit, cache_file: str):
        self._base = base
        self._cache_template = cache_file
        self._configure_paths()
        self._reader = None
        self._writer = None
        self._bytes = 0

    def _configure_paths(self) -> None:
        part = getattr(self._base, "part_index", 0)
        npart = getattr(self._base, "num_parts", 1)
        self._cache_path = f"{self._cache_template}.p{part}-{npart}"
        self._done_path = self._cache_path + ".done"

    @property
    def _cached(self) -> bool:
        return os.path.exists(self._done_path)

    def before_first(self) -> None:
        self._recbuf = None
        self._recpos = 0
        self._bytes = 0
        if self._writer is not None:
            # torn pass: discard partial cache
            self._writer.close()
            self._writer = None
            try:
                os.remove(self._cache_path + ".tmp")
            except OSError:
                pass
        if self._reader is not None:
            self._reader.close()
            self._reader = None
        if not self._cached:
            self._base.before_first()
            self._writer = open(self._cache_path + ".tmp", "wb")
        else:
            self._reader = open(self._cache_path, "rb")

    def next_chunk(self) -> Optional[bytes]:
        if self._reader is None and self._writer is None:
            self.before_first()
        if self._reader is not None:
            head = self._reader.read(8)
            if len(head) < 8:
                return None
            (n,) = struct.unpack("<Q", head)
            chunk = self._reader.read(n)
            check(len(chunk) == n, "cache file truncated")
            self._bytes += n
            return chunk
        chunk = self._base.next_chunk()
        if chunk is None:
            # commit the cache
            self._writer.close()
            self._writer = None
            os.replace(self._cache_path + ".tmp", self._cache_path)
            open(self._done_path, "wb").close()
            return None
        self._writer.write(struct.pack("<Q", len(chunk)))
        self._writer.write(chunk)
        self._bytes += len(chunk)
        return chunk

    def next_record(self) -> Optional[bytes]:
        while True:
            buf = getattr(self, "_recbuf", None)
            pos = getattr(self, "_recpos", 0)
            if buf is not None and pos < len(buf):
                self._recpos = pos + 1
                return buf[pos]
            chunk = self.next_chunk()
            if chunk is None:
                self._recbuf = None
                return None
            self._recbuf = list(self.extract_records(chunk))
            self._recpos = 0

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        return self._base.extract_records(chunk)

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        self._base.reset_partition(part_index, num_parts)
        self._configure_paths()
        self._reader = None
        self._writer = None
        self.before_first()

    def get_total_size(self) -> int:
        return self._base.get_total_size()

    @property
    def bytes_read(self) -> int:
        return self._bytes
