"""Background chunk-prefetch wrapper for any InputSplit.

Reference: src/io/threaded_input_split.h — ThreadedInputSplit wraps an
InputSplitBase in a ThreadedIter<Chunk> so disk/network reads overlap with
parsing on the consumer thread.
"""

from __future__ import annotations

from typing import Iterator, Optional

from dmlc_tpu.data.threaded_iter import ThreadedIter
from dmlc_tpu.io.input_split import InputSplit

__all__ = ["ThreadedInputSplit"]


class ThreadedInputSplit(InputSplit):
    def __init__(self, base: InputSplit, max_capacity: int = 4):
        self._base = base
        self._iter = ThreadedIter(max_capacity=max_capacity,
                                  name="split.chunks")
        self._iter.init(base.next_chunk, base.before_first)
        self._recbuf = []
        self._recpos = 0

    def next_chunk(self) -> Optional[bytes]:
        return self._iter.next()

    def next_record(self) -> Optional[bytes]:
        while self._recpos >= len(self._recbuf):
            chunk = self.next_chunk()
            if chunk is None:
                return None
            self._recbuf = list(self._base.extract_records(chunk))
            self._recpos = 0
        rec = self._recbuf[self._recpos]
        self._recpos += 1
        return rec

    def before_first(self) -> None:
        self._iter.before_first()
        self._recbuf, self._recpos = [], 0

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        self._iter.destroy()
        self._base.reset_partition(part_index, num_parts)
        self._iter = ThreadedIter(max_capacity=4, name="split.chunks")
        self._iter.init(self._base.next_chunk, self._base.before_first)
        self._recbuf, self._recpos = [], 0

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        return self._base.extract_records(chunk)

    def get_total_size(self) -> int:
        return self._base.get_total_size()

    @property
    def bytes_read(self) -> int:
        return self._base.bytes_read

    def destroy(self) -> None:
        self._iter.destroy()
