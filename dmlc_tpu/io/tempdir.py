"""RAII temporary directory (reference: include/dmlc/filesystem.h —
dmlc::TemporaryDirectory, mkdtemp + recursive delete)."""

from __future__ import annotations

import os
import shutil
import tempfile

__all__ = ["TemporaryDirectory"]


class TemporaryDirectory:
    """Create on construction, recursively delete on close/del/context-exit.

    >>> with TemporaryDirectory() as td:
    ...     open(os.path.join(td.path, "x"), "w").close()
    """

    def __init__(self, prefix: str = "dmlc_tpu.", verbose: bool = False):
        self.path = tempfile.mkdtemp(prefix=prefix)
        self._verbose = verbose

    def __enter__(self) -> "TemporaryDirectory":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        if self.path and os.path.isdir(self.path):
            if self._verbose:
                from dmlc_tpu.utils.logging import log_info
                log_info(f"deleting temporary directory {self.path}")
            shutil.rmtree(self.path, ignore_errors=True)
        self.path = ""

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass
