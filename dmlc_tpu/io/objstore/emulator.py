"""On-disk fake object store — the test/bench backend of the remote
I/O plane.

The container this repo grows in has no network and no cloud
credentials (SURVEY §7), so the ``obj://`` plane is exercised against
this emulator: a directory of ``<root>/<bucket>/<key>`` files behind
the same client protocol a real S3/GCS backend would implement
(``get``/``head``/``list``/``put``). Two things make it a *model*
rather than a stub:

- **latency/bandwidth shaping** — every GET *and every PUT* pays
  ``latency_s`` plus ``bytes / bandwidth`` of sleep, so cold-vs-warm
  epoch benchmarks (bench_suite config 11) and multipart-vs-single-shot
  write benchmarks (config 21) measure a believable wire, not a local
  read;
- **first-class chaos** — the client seams (``io.objstore.get`` etc.,
  see fs.py) run under ``resilience.guarded()``, so an armed
  :class:`~dmlc_tpu.resilience.inject.FaultPlan` targets emulator
  traffic exactly as it would real wire calls (ioerror, delay,
  truncate, crash), with the emulator's request counters proving what
  actually hit the "network".

Counters (``gets``/``get_bytes``/``heads``/``lists``/``puts``/
``put_bytes``/``put_parts``) are the ground truth for the
wire-free-second-epoch acceptance and the per-part multipart
accounting: a page-store hit rate can lie, a GET/PUT counter cannot.

Multipart protocol (the write-plane mirror of the ranged-GET read
plane; see io/objstore/multipart.py for the client-side writer):
``create_multipart`` opens an upload (parts stage under a
``.mpu/<upload_id>/`` area the listings never show),
``put_part`` uploads one part (throttled + counted like any wire PUT),
``complete_multipart`` concatenates the parts into the final key
atomically (a metadata op — latency only, no bandwidth charge, like
S3's CompleteMultipartUpload), and ``abort_multipart`` removes the
staged parts without the final key ever existing. ``list_uploads``
exposes in-flight uploads so the stale sweep can reap a dead writer's
orphans (upload ids embed the writer pid — the pagestore liveness
rule).
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

from dmlc_tpu.obs import rpc as _rpc
from dmlc_tpu.utils.logging import DMLCError, check

__all__ = ["ObjectInfo", "EmulatedObjectStore"]


@dataclass
class ObjectInfo:
    """What a HEAD returns: enough for stat, listing, and the
    fingerprint stamp (etag doubles as the change token)."""
    key: str
    size: int
    mtime_ns: int

    @property
    def etag(self) -> str:
        return f"{self.size}-{self.mtime_ns}"


class EmulatedObjectStore:
    """Bucket/key object store over a local directory.

    Thread-safe; ranged GETs are byte-exact (``get(b, k, start, end)``
    returns ``data[start:end]``). Keys may contain '/' — they map to
    nested directories, and :meth:`list` is prefix-recursive the way
    object-store listings are.
    """

    def __init__(self, root: str, latency_s: float = 0.0,
                 bandwidth_gbps: Optional[float] = None):
        self.root = os.path.abspath(root)
        self.latency_s = float(latency_s)
        self.bandwidth_gbps = bandwidth_gbps
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self.gets = 0
        self.get_bytes = 0
        self.heads = 0
        self.lists = 0
        self.puts = 0
        self.put_bytes = 0
        self.put_parts = 0

    # -- layout

    def _path(self, bucket: str, key: str = "") -> str:
        check(bucket and "/" not in bucket and ".." not in bucket,
              f"objstore: invalid bucket {bucket!r}")
        check(".." not in key.split("/"),
              f"objstore: invalid key {key!r}")
        p = os.path.join(self.root, bucket, *key.split("/")) if key \
            else os.path.join(self.root, bucket)
        return p

    def _throttle(self, nbytes: int) -> None:
        d = self.latency_s
        if self.bandwidth_gbps:
            d += nbytes / (self.bandwidth_gbps * 1e9)
        if d > 0:
            time.sleep(d)

    # -- client protocol

    def put(self, bucket: str, key: str, data: bytes) -> ObjectInfo:
        """Single-shot PUT. Pays the same latency/bandwidth model as a
        GET — the wire is symmetric, which is what makes multipart's
        parallel parts measurably faster than one serial upload."""
        p = self._path(bucket, key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        # emulated_server models the serving half of the hop (obs.rpc):
        # the disk write + modeled wire time IS the handle time a real
        # endpoint would echo, so single-process benches decompose
        # client latency exactly like wire runs
        with _rpc.emulated_server("put"):
            tmp = p + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(data)
            self._throttle(len(data))
            os.replace(tmp, p)
        with self._lock:
            self.puts += 1
            self.put_bytes += len(data)
        return self.head(bucket, key, count=False)

    def put_file(self, bucket: str, key: str, src_path: str) -> ObjectInfo:
        """Upload a local file (bench/test corpus loader)."""
        with open(src_path, "rb") as f:
            return self.put(bucket, key, f.read())

    # -- multipart upload (the write-plane protocol)

    def _mpu_dir(self, bucket: str, upload_id: str) -> str:
        check(upload_id and "/" not in upload_id
              and ".." not in upload_id,
              f"objstore: invalid upload id {upload_id!r}")
        return os.path.join(self._path(bucket), ".mpu", upload_id)

    def create_multipart(self, bucket: str, key: str) -> str:
        """Open a multipart upload for ``key``; returns the upload id.
        The id embeds the writer pid (``p<pid>-<nonce>``) so the stale
        sweep can reap a crashed writer's parts by the one pagestore
        liveness rule."""
        self._path(bucket, key)  # validate bucket/key
        nonce = os.urandom(4).hex()
        upload_id = f"p{os.getpid()}-{nonce}"
        d = self._mpu_dir(bucket, upload_id)
        os.makedirs(d, exist_ok=True)
        # the manifest records the target key: list_uploads/sweep can
        # report WHAT a dead writer was uploading, not just that it was
        with open(os.path.join(d, "key"), "w") as f:
            f.write(key)
        return upload_id

    def put_part(self, bucket: str, key: str, upload_id: str,
                 part_num: int, data: bytes) -> None:
        """Upload one part (0-based). Throttled and counted like any
        wire PUT — parts are where multipart's bytes actually move."""
        check(part_num >= 0, "objstore: negative part number")
        d = self._mpu_dir(bucket, upload_id)
        os.makedirs(d, exist_ok=True)
        p = os.path.join(d, f"part-{part_num:05d}")
        with _rpc.emulated_server("put"):
            tmp = p + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(data)
            self._throttle(len(data))
            os.replace(tmp, p)
        with self._lock:
            self.put_parts += 1
            self.put_bytes += len(data)

    def complete_multipart(self, bucket: str, key: str, upload_id: str,
                           nparts: int) -> ObjectInfo:
        """Concatenate parts ``0..nparts-1`` into the final key
        atomically and drop the staged parts. A metadata op: latency
        only, no bandwidth charge (the bytes already moved per part).
        A missing part raises FileNotFoundError — non-retryable, the
        upload is torn and the caller must abort."""
        d = self._mpu_dir(bucket, upload_id)
        p = self._path(bucket, key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as out:
            for n in range(nparts):
                part = os.path.join(d, f"part-{n:05d}")
                if not os.path.isfile(part):
                    out.close()
                    os.remove(tmp)
                    raise FileNotFoundError(
                        f"objstore: multipart {bucket}/{key} upload "
                        f"{upload_id} missing part {n}")
                with open(part, "rb") as f:
                    shutil.copyfileobj(f, out)
        self._throttle(0)
        os.replace(tmp, p)
        shutil.rmtree(d, ignore_errors=True)
        with self._lock:
            self.puts += 1
        return self.head(bucket, key, count=False)

    def abort_multipart(self, bucket: str, key: str,
                        upload_id: str) -> None:
        """Drop an upload's staged parts; the final key never appears.
        Idempotent (aborting an unknown upload is a no-op)."""
        shutil.rmtree(self._mpu_dir(bucket, upload_id),
                      ignore_errors=True)

    def list_uploads(self, bucket: str) -> List[Tuple[str, str]]:
        """In-flight multipart uploads as ``(upload_id, key)`` — the
        sweep's view of what a crashed writer left behind."""
        base = os.path.join(self._path(bucket), ".mpu")
        if not os.path.isdir(base):
            return []
        out: List[Tuple[str, str]] = []
        for upload_id in sorted(os.listdir(base)):
            manifest = os.path.join(base, upload_id, "key")
            try:
                with open(manifest) as f:
                    target = f.read()
            except OSError:
                target = ""
            out.append((upload_id, target))
        return out

    def buckets(self) -> List[str]:
        """Every bucket in the store — lets the bucketless
        :func:`~dmlc_tpu.io.objstore.multipart.sweep_uploads` cover the
        whole root."""
        return sorted(n for n in os.listdir(self.root)
                      if os.path.isdir(os.path.join(self.root, n)))

    def delete(self, bucket: str, key: str) -> bool:
        """Remove one object; True when it existed (object stores have
        DELETE — re-saves of a checkpoint step invalidate their COMMIT
        marker through it)."""
        p = self._path(bucket, key)
        try:
            os.remove(p)
            return True
        except FileNotFoundError:
            return False

    def head(self, bucket: str, key: str,
             count: bool = True) -> ObjectInfo:
        p = self._path(bucket, key)
        if not os.path.isfile(p):
            raise FileNotFoundError(
                f"objstore: no object {bucket}/{key}")
        st = os.stat(p)
        if count:
            with self._lock:
                self.heads += 1
        return ObjectInfo(key=key, size=st.st_size,
                          mtime_ns=st.st_mtime_ns)

    def is_prefix(self, bucket: str, key: str = "") -> bool:
        """Whether any object lives under ``key`` as a prefix
        (object-store "directory" semantics)."""
        p = self._path(bucket, key)
        return os.path.isdir(p)

    def list(self, bucket: str, prefix: str = "") -> List[ObjectInfo]:
        """All objects under ``prefix``, key-sorted (recursive, the
        object-store listing shape). In-flight multipart parts (the
        ``.mpu`` staging area) are never listed — an aborted or torn
        upload is invisible, exactly like a real object store."""
        base = self._path(bucket)
        start = self._path(bucket, prefix) if prefix else base
        with self._lock:
            self.lists += 1
        if not os.path.isdir(start):
            if os.path.isfile(start):
                return [self.head(bucket, prefix, count=False)]
            return []
        out: List[ObjectInfo] = []
        for dirpath, dirnames, filenames in os.walk(start):
            if dirpath == base and ".mpu" in dirnames:
                dirnames.remove(".mpu")
            dirnames.sort()
            for name in sorted(filenames):
                full = os.path.join(dirpath, name)
                key = os.path.relpath(full, base).replace(os.sep, "/")
                st = os.stat(full)
                out.append(ObjectInfo(key=key, size=st.st_size,
                                      mtime_ns=st.st_mtime_ns))
        out.sort(key=lambda o: o.key)
        return out

    def _read_range(self, bucket: str, key: str, start: int,
                    end: Optional[int]) -> bytes:
        check(start >= 0, "objstore: negative range start")
        p = self._path(bucket, key)
        if not os.path.isfile(p):
            raise FileNotFoundError(
                f"objstore: no object {bucket}/{key}")
        size = os.path.getsize(p)
        stop = size if end is None else min(end, size)
        if stop < start:
            raise DMLCError(
                f"objstore: bad range [{start}, {end}) for "
                f"{bucket}/{key} (size {size})")
        with open(p, "rb") as f:
            f.seek(start)
            return f.read(stop - start)

    def get(self, bucket: str, key: str, start: int = 0,
            end: Optional[int] = None) -> bytes:
        """Ranged GET: bytes ``[start, end)`` of the object (``end``
        None = to the end). Pays the latency/bandwidth model."""
        with _rpc.emulated_server("get"):
            data = self._read_range(bucket, key, start, end)
            self._throttle(len(data))
        with self._lock:
            self.gets += 1
            self.get_bytes += len(data)
        return data

    def get_encoded(self, bucket: str, key: str, start: int, end: int,
                    level: int) -> bytes:
        """Ranged GET with transfer encoding (the HTTP
        Content-Encoding shape): the payload is the requested range
        wrapped in an ``io.codec`` page frame, and the wire model —
        throttle AND the ``get_bytes`` ground-truth counter — charges
        the ENCODED size. That is what makes a compressed cold epoch
        genuinely move fewer modeled wire bytes; the caller decodes
        under its retry seam and serves the raw range."""
        from dmlc_tpu.io.codec import encode_page
        with _rpc.emulated_server("get"):
            data = encode_page(
                self._read_range(bucket, key, start, end), level)
            self._throttle(len(data))
        with self._lock:
            self.gets += 1
            self.get_bytes += len(data)
        return data

    # -- test/bench helpers

    def reset_counters(self) -> None:
        with self._lock:
            self.gets = self.get_bytes = 0
            self.heads = self.lists = self.puts = 0
            self.put_bytes = self.put_parts = 0

    def counters(self) -> dict:
        with self._lock:
            return {"gets": self.gets, "get_bytes": self.get_bytes,
                    "heads": self.heads, "lists": self.lists,
                    "puts": self.puts, "put_bytes": self.put_bytes,
                    "put_parts": self.put_parts}
