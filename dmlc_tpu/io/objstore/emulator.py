"""On-disk fake object store — the test/bench backend of the remote
I/O plane.

The container this repo grows in has no network and no cloud
credentials (SURVEY §7), so the ``obj://`` plane is exercised against
this emulator: a directory of ``<root>/<bucket>/<key>`` files behind
the same client protocol a real S3/GCS backend would implement
(``get``/``head``/``list``/``put``). Two things make it a *model*
rather than a stub:

- **latency/bandwidth shaping** — every GET pays ``latency_s`` plus
  ``bytes / bandwidth`` of sleep, so cold-vs-warm epoch benchmarks
  (bench_suite config 11) measure a believable wire, not a local read;
- **first-class chaos** — the client seams (``io.objstore.get`` etc.,
  see fs.py) run under ``resilience.guarded()``, so an armed
  :class:`~dmlc_tpu.resilience.inject.FaultPlan` targets emulator
  traffic exactly as it would real wire calls (ioerror, delay,
  truncate, crash), with the emulator's request counters proving what
  actually hit the "network".

Counters (``gets``/``get_bytes``/``heads``/``lists``/``puts``) are the
ground truth for the wire-free-second-epoch acceptance: a page-store
hit rate can lie, a GET counter cannot.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

from dmlc_tpu.utils.logging import DMLCError, check

__all__ = ["ObjectInfo", "EmulatedObjectStore"]


@dataclass
class ObjectInfo:
    """What a HEAD returns: enough for stat, listing, and the
    fingerprint stamp (etag doubles as the change token)."""
    key: str
    size: int
    mtime_ns: int

    @property
    def etag(self) -> str:
        return f"{self.size}-{self.mtime_ns}"


class EmulatedObjectStore:
    """Bucket/key object store over a local directory.

    Thread-safe; ranged GETs are byte-exact (``get(b, k, start, end)``
    returns ``data[start:end]``). Keys may contain '/' — they map to
    nested directories, and :meth:`list` is prefix-recursive the way
    object-store listings are.
    """

    def __init__(self, root: str, latency_s: float = 0.0,
                 bandwidth_gbps: Optional[float] = None):
        self.root = os.path.abspath(root)
        self.latency_s = float(latency_s)
        self.bandwidth_gbps = bandwidth_gbps
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self.gets = 0
        self.get_bytes = 0
        self.heads = 0
        self.lists = 0
        self.puts = 0

    # -- layout

    def _path(self, bucket: str, key: str = "") -> str:
        check(bucket and "/" not in bucket and ".." not in bucket,
              f"objstore: invalid bucket {bucket!r}")
        check(".." not in key.split("/"),
              f"objstore: invalid key {key!r}")
        p = os.path.join(self.root, bucket, *key.split("/")) if key \
            else os.path.join(self.root, bucket)
        return p

    def _throttle(self, nbytes: int) -> None:
        d = self.latency_s
        if self.bandwidth_gbps:
            d += nbytes / (self.bandwidth_gbps * 1e9)
        if d > 0:
            time.sleep(d)

    # -- client protocol

    def put(self, bucket: str, key: str, data: bytes) -> ObjectInfo:
        p = self._path(bucket, key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)
        with self._lock:
            self.puts += 1
        return self.head(bucket, key, count=False)

    def put_file(self, bucket: str, key: str, src_path: str) -> ObjectInfo:
        """Upload a local file (bench/test corpus loader)."""
        with open(src_path, "rb") as f:
            return self.put(bucket, key, f.read())

    def head(self, bucket: str, key: str,
             count: bool = True) -> ObjectInfo:
        p = self._path(bucket, key)
        if not os.path.isfile(p):
            raise FileNotFoundError(
                f"objstore: no object {bucket}/{key}")
        st = os.stat(p)
        if count:
            with self._lock:
                self.heads += 1
        return ObjectInfo(key=key, size=st.st_size,
                          mtime_ns=st.st_mtime_ns)

    def is_prefix(self, bucket: str, key: str = "") -> bool:
        """Whether any object lives under ``key`` as a prefix
        (object-store "directory" semantics)."""
        p = self._path(bucket, key)
        return os.path.isdir(p)

    def list(self, bucket: str, prefix: str = "") -> List[ObjectInfo]:
        """All objects under ``prefix``, key-sorted (recursive, the
        object-store listing shape)."""
        base = self._path(bucket)
        start = self._path(bucket, prefix) if prefix else base
        with self._lock:
            self.lists += 1
        if not os.path.isdir(start):
            if os.path.isfile(start):
                return [self.head(bucket, prefix, count=False)]
            return []
        out: List[ObjectInfo] = []
        for dirpath, dirnames, filenames in os.walk(start):
            dirnames.sort()
            for name in sorted(filenames):
                full = os.path.join(dirpath, name)
                key = os.path.relpath(full, base).replace(os.sep, "/")
                st = os.stat(full)
                out.append(ObjectInfo(key=key, size=st.st_size,
                                      mtime_ns=st.st_mtime_ns))
        out.sort(key=lambda o: o.key)
        return out

    def _read_range(self, bucket: str, key: str, start: int,
                    end: Optional[int]) -> bytes:
        check(start >= 0, "objstore: negative range start")
        p = self._path(bucket, key)
        if not os.path.isfile(p):
            raise FileNotFoundError(
                f"objstore: no object {bucket}/{key}")
        size = os.path.getsize(p)
        stop = size if end is None else min(end, size)
        if stop < start:
            raise DMLCError(
                f"objstore: bad range [{start}, {end}) for "
                f"{bucket}/{key} (size {size})")
        with open(p, "rb") as f:
            f.seek(start)
            return f.read(stop - start)

    def get(self, bucket: str, key: str, start: int = 0,
            end: Optional[int] = None) -> bytes:
        """Ranged GET: bytes ``[start, end)`` of the object (``end``
        None = to the end). Pays the latency/bandwidth model."""
        data = self._read_range(bucket, key, start, end)
        self._throttle(len(data))
        with self._lock:
            self.gets += 1
            self.get_bytes += len(data)
        return data

    def get_encoded(self, bucket: str, key: str, start: int, end: int,
                    level: int) -> bytes:
        """Ranged GET with transfer encoding (the HTTP
        Content-Encoding shape): the payload is the requested range
        wrapped in an ``io.codec`` page frame, and the wire model —
        throttle AND the ``get_bytes`` ground-truth counter — charges
        the ENCODED size. That is what makes a compressed cold epoch
        genuinely move fewer modeled wire bytes; the caller decodes
        under its retry seam and serves the raw range."""
        from dmlc_tpu.io.codec import encode_page
        data = encode_page(self._read_range(bucket, key, start, end),
                           level)
        self._throttle(len(data))
        with self._lock:
            self.gets += 1
            self.get_bytes += len(data)
        return data

    # -- test/bench helpers

    def reset_counters(self) -> None:
        with self._lock:
            self.gets = self.get_bytes = 0
            self.heads = self.lists = self.puts = 0

    def counters(self) -> dict:
        with self._lock:
            return {"gets": self.gets, "get_bytes": self.get_bytes,
                    "heads": self.heads, "lists": self.lists,
                    "puts": self.puts}
