"""Parallel multipart PUT: the objstore write plane.

Reference: src/io/s3_filesys.cc — upstream's S3 ``WriteStream`` is a
multipart upload accumulating fixed-size parts; this is the same shape
behind the pluggable client protocol (emulator + HTTP backends both
speak it — see emulator.py's multipart verbs and http_client.py's
``?dmlc-upload=`` convention).

:class:`MultipartWriter` splits a byte stream into fixed ``part_bytes``
parts uploaded by a bounded worker pool. Every wire call runs under
the ``io.objstore.put`` resilience seam:

- a transient part failure (or an injected ioerror/truncate) retries
  JUST that part, byte-identically — the part buffer is immutable and
  re-sent verbatim, never re-sliced;
- faults past the retry ladder ABORT the whole upload: the staged
  parts are discarded and no object (partial or otherwise) becomes
  visible at the key — readers see the previous generation or nothing;
- a writer that crashes mid-upload leaves parts staged under its
  pid-embedded ``upload_id`` (``p<pid>-<nonce>``):
  :func:`sweep_uploads` reaps them by the ONE pagestore liveness rule
  (``_pid_dead``), riding the existing stale-sweep machinery.

Telemetry (rendered ``dmlc_objstore_*_total`` on /metrics):
``objstore.put.parts`` / ``objstore.put.bytes`` per part landed,
``objstore.put.retries`` per re-sent attempt, ``objstore.put.aborts``
per abandoned upload, ``objstore.put`` per object completed.

The FS surface picks this path automatically:
``create_stream("obj://...", "w")`` spills into a multipart upload
once the buffered bytes cross ``options()["put_part_bytes"]`` (and the
configured client speaks multipart); smaller objects stay single-shot
PUTs. ``ShardedCheckpoint`` writes per-shard streams through the same
seam — device-direct, no whole-tree host staging (docs/remote_io.md
"Write path & multipart").
"""

from __future__ import annotations

import os
from typing import List, Optional

from dmlc_tpu.io.stream import Stream
from dmlc_tpu.resilience import inject as _inject
from dmlc_tpu.resilience.policy import guarded
from dmlc_tpu.utils.logging import check

__all__ = ["MultipartWriter", "supports_multipart", "sweep_uploads"]

_MULTIPART_VERBS = ("create_multipart", "put_part", "complete_multipart",
                    "abort_multipart")


def supports_multipart(client_obj) -> bool:
    """True when the client speaks the full multipart verb set (the
    hasattr probe, same convention as ``get_encoded``)."""
    return all(hasattr(client_obj, v) for v in _MULTIPART_VERBS)


def _count(which: str, n: int = 1) -> None:
    try:
        from dmlc_tpu.obs.metrics import REGISTRY
        REGISTRY.counter(f"objstore.{which}").inc(n)
    except Exception:  # noqa: BLE001 — telemetry must not break I/O
        pass


class MultipartWriter(Stream):
    """Write-only stream uploading fixed-size parts concurrently.

    ``write()`` buffers; each time ``part_bytes`` accumulate, that part
    is handed to a bounded pool (``parallel`` workers, at most
    ``2 * parallel`` parts in flight so memory stays bounded).
    ``close()`` flushes the remainder part, waits for every part, and
    completes the upload — the object becomes visible atomically, or
    not at all: any part failure past the retry ladder aborts the
    upload and re-raises."""

    def __init__(self, client_obj, bucket: str, key: str, path: str,
                 part_bytes: int = 8 << 20, parallel: int = 4):
        check(part_bytes >= 1, "multipart: part_bytes must be >= 1")
        check(parallel >= 1, "multipart: parallel must be >= 1")
        check(supports_multipart(client_obj),
              f"multipart: client {type(client_obj).__name__} does not "
              "speak the multipart verbs")
        self._c = client_obj
        self._bucket = bucket
        self._key = key
        self.path = path
        self._part_bytes = int(part_bytes)
        self._parallel = int(parallel)
        self._buf = bytearray()
        self._nparts = 0
        self._futures: List = []
        self._pool = None
        self._closed = False
        self._aborted = False
        self._upload_id = guarded(
            "io.objstore.put",
            lambda: client_obj.create_multipart(bucket, key))

    # -- Stream

    def read(self, nbytes: int) -> bytes:
        from dmlc_tpu.utils.logging import DMLCError
        raise DMLCError("multipart: write-only stream")

    def write(self, data) -> int:
        check(not self._closed and not self._aborted,
              "multipart: write after close/abort")
        # slice parts straight from the input: one copy per part
        # (the immutable bytes handed to the pool), never a growing
        # carry buffer shifted per part
        mv = memoryview(data)
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        n = len(mv)
        pb = self._part_bytes
        off = 0
        if self._buf:  # top up the carry to one full part first
            off = min(pb - len(self._buf), n)
            self._buf += mv[:off]
            if len(self._buf) == pb:
                self._submit(bytes(self._buf))
                self._buf = bytearray()
        while n - off >= pb:
            self._submit(bytes(mv[off:off + pb]))
            off += pb
        if off < n:
            self._buf += mv[off:]
        return n

    def close(self) -> None:
        if self._closed or self._aborted:
            return
        self._closed = True
        try:
            if self._buf:
                self._submit(bytes(self._buf))
                self._buf = bytearray()
            for f in self._futures:
                f.result()  # re-raises the first part failure
            guarded("io.objstore.put",
                    lambda: self._c.complete_multipart(
                        self._bucket, self._key, self._upload_id,
                        self._nparts))
            _count("put")
        except BaseException:
            self._abort()
            raise
        finally:
            self._shutdown_pool()

    def abort(self) -> None:
        """Abandon the upload: no object appears at the key, staged
        parts are discarded. Idempotent; safe after a failed close."""
        if self._aborted:
            return
        self._closed = True
        self._abort()
        self._shutdown_pool()

    # -- internals

    def _abort(self) -> None:
        self._aborted = True
        for f in self._futures:
            f.cancel()
        for f in self._futures:
            if not f.cancelled():
                try:
                    f.result()
                except BaseException:  # noqa: BLE001 — already failing
                    pass
        try:
            self._c.abort_multipart(self._bucket, self._key,
                                    self._upload_id)
        except Exception:  # noqa: BLE001 — best-effort; sweep reaps
            pass
        _count("put.aborts")

    def _shutdown_pool(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def _executor(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=self._parallel,
                thread_name_prefix="dmlc_tpu.objstore.put")
        return self._pool

    def _submit(self, part: bytes) -> None:
        ix = self._nparts
        self._nparts += 1
        # bound in-flight parts (and their buffers): wait for the
        # oldest before queueing past 2x the pool width
        live = [f for f in self._futures if not f.done()]
        while len(live) >= 2 * self._parallel:
            live[0].result()
            live = [f for f in self._futures if not f.done()]
        self._futures.append(
            self._executor().submit(self._put_part, ix, part))

    def _put_part(self, ix: int, part: bytes) -> None:
        """Upload one part under the ``io.objstore.put`` seam. The
        part bytes are immutable: every retry re-sends them verbatim.
        An injected truncation is detected HERE (the writer owns the
        bytes) and raised as a transient IOError so the site's policy
        retries just this part."""
        attempts = 0

        def attempt():
            nonlocal attempts
            attempts += 1
            payload = _inject.corrupt("io.objstore.put", part)
            if len(payload) != len(part):
                raise IOError(
                    f"objstore: torn part {ix} on {self.path}: sent "
                    f"{len(payload)}/{len(part)} bytes")
            self._c.put_part(self._bucket, self._key, self._upload_id,
                             ix, payload)

        guarded("io.objstore.put", attempt)
        if attempts > 1:
            _count("put.retries", attempts - 1)
        _count("put.parts")
        _count("put.bytes", len(part))


def sweep_uploads(client_obj=None, bucket: Optional[str] = None) -> int:
    """Reap in-flight uploads whose writer process is dead — the
    multipart leg of the stale sweep. Upload ids embed the writer pid
    (``p<pid>-<nonce>``); liveness is the ONE pagestore rule
    (``_pid_dead``), so a crashed writer's staged parts go the same
    way its orphaned .tmp pages do. Live writers' uploads are left
    alone. Returns uploads aborted.

    ``client_obj=None`` resolves the configured client
    (:func:`dmlc_tpu.io.objstore.client`); ``bucket=None`` sweeps
    every bucket the store lists at its root (clients without a
    ``buckets()`` probe sweep nothing without an explicit bucket)."""
    from dmlc_tpu.io.pagestore import _pid_dead
    if client_obj is None:
        from dmlc_tpu.io.objstore.fs import client
        client_obj = client()
    if client_obj is None or not hasattr(client_obj, "list_uploads"):
        return 0
    if bucket is None:
        if not hasattr(client_obj, "buckets"):
            return 0
        buckets = list(client_obj.buckets())
    else:
        buckets = [bucket]
    reaped = 0
    for b in buckets:
        try:
            uploads = client_obj.list_uploads(b)
        except Exception:  # noqa: BLE001 — sweep is best-effort
            continue
        for upload_id, key in uploads:
            pid = _upload_pid(upload_id)
            if pid is None or pid == os.getpid() or not _pid_dead(pid):
                continue
            try:
                client_obj.abort_multipart(b, key, upload_id)
                reaped += 1
            except Exception:  # noqa: BLE001 — next sweep retries
                pass
    return reaped


def _upload_pid(upload_id: str) -> Optional[int]:
    if not upload_id.startswith("p"):
        return None
    head = upload_id[1:].split("-", 1)[0]
    return int(head) if head.isdigit() else None
