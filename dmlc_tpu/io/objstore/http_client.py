"""The REAL networked object-store client: HTTP ranged GETs (stdlib
``http.client``), the wire PR 6 deferred.

Reference: src/io/s3_filesys.cc — upstream's S3 backend is CURL +
request signing behind the one ``FileSystem`` interface. This module
is the equivalent rung for a container with no cloud SDKs: a plain
HTTP(S) object endpoint (S3-compatible gateways, an nginx bucket
mirror, a dmlc-aware proxy) spoken with nothing but the standard
library, behind the SAME client protocol the emulator implements — so
``ObjectSeekStream``'s block/coalesce/hydrate/peer machinery, the
``io.objstore.*`` retry seams, and every chaos plan apply unchanged.

Import-optional by design: nothing in the package imports this module
until ``objstore.configure(endpoint=...)`` (or the
``DMLC_TPU_OBJSTORE_ENDPOINT`` env contract) names an endpoint — the
emulator remains the test backend, and no new dependency exists
(``http.client`` is stdlib; the lint gate confines it to the objstore
client modules).

Protocol mapping (objects live at ``<endpoint>/<bucket>/<key>``):

- ``get(bucket, key, start, end)`` — ``GET`` with
  ``Range: bytes=start-(end-1)``; a 206 returns the range, a 200 from
  a Range-ignoring server is sliced locally, and a body shorter than
  its ``Content-Length`` raises IOError INSIDE the call — the
  ``io.objstore.get`` seam's short-range check and retry ladder see
  exactly what they see from the emulator;
- ``head(bucket, key)`` — ``HEAD``: size from ``Content-Length``,
  change token from ``ETag`` (falling back to ``size-mtime``), mtime
  from ``X-Dmlc-Mtime-Ns`` or ``Last-Modified``;
- ``put(bucket, key, data)`` — ``PUT`` (2xx = success);
- multipart (only when constructed with ``multipart=True`` — the
  dmlc-gateway write convention, gated per-instance exactly like
  ``encoded``): the upload id is generated client-side
  (``p<pid>-<nonce>``, pid-embedded for the stale sweep), parts travel
  as ``PUT <bucket>/<key>?dmlc-upload=<id>&dmlc-part=<n>``, the final
  object materializes with ``POST ?dmlc-upload=<id>&dmlc-complete=
  <nparts>`` and a torn upload is dropped with ``POST
  ?dmlc-upload=<id>&dmlc-abort=1``; ``list_uploads`` reads ``GET
  <bucket>?dmlc-uploads=1`` so the sweep sees orphaned uploads;
- ``delete(bucket, key)`` — ``DELETE`` (404 = already gone);
- ``list(bucket, prefix)`` / ``is_prefix`` — ``GET
  <endpoint>/<bucket>?dmlc-list=<prefix>`` expecting a JSON array of
  ``{key, size, mtime_ns}``: the listing convention a dmlc-aware
  gateway provides. A plain static server without it raises
  ``DMLCError`` (single-object URIs — the streaming read path — never
  need a listing);
- ``get_encoded(...)`` (only when constructed with ``encoded=True``)
  — the ``io/codec.py`` frame riding HTTP Content-Encoding style: the
  request advertises ``X-Dmlc-Accept-Codec: dtpc``, a reply stamped
  ``X-Dmlc-Codec: dtpc`` is returned as the codec frame (decoded
  inside the ``io.objstore.get`` retry seam, exactly like the
  emulator's modeled transfer coding), and a reply without the stamp
  is wrapped as a stored frame so the decode stays unambiguous.

Auth is a hook, not a policy: pass ``auth`` as a static header dict or
a zero-arg callable returning one (called per request, so rotating
tokens just work); e.g. ``auth=lambda: {"Authorization": f"Bearer "
f"{token()}"}``.
"""

from __future__ import annotations

import http.client
import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple
from urllib.parse import quote, urlsplit

from dmlc_tpu.utils.logging import DMLCError, check

__all__ = ["HttpObjectStoreClient", "RemoteObjectInfo"]


@dataclass
class RemoteObjectInfo:
    """What a HEAD/listing returns — the emulator's ``ObjectInfo``
    shape with the server's own etag when it sent one."""
    key: str
    size: int
    mtime_ns: int
    etag: str = ""

    def __post_init__(self) -> None:
        if not self.etag:
            self.etag = f"{self.size}-{self.mtime_ns}"


def _parse_http_date_ns(value: Optional[str]) -> int:
    """``Last-Modified`` -> epoch ns (0 when absent/unparseable — the
    etag is the change token; mtime is advisory for fingerprints)."""
    if not value:
        return 0
    try:
        from email.utils import parsedate_to_datetime
        return int(parsedate_to_datetime(value).timestamp() * 1e9)
    except (TypeError, ValueError, OverflowError):
        return 0


class HttpObjectStoreClient:
    """Ranged-GET object client over one HTTP(S) endpoint."""

    def __init__(self, endpoint: str, auth=None, timeout_s: float = 10.0,
                 encoded: bool = False, multipart: bool = False):
        u = urlsplit(endpoint if "://" in endpoint
                     else f"http://{endpoint}")
        check(u.scheme in ("http", "https"),
              f"objstore http: unsupported scheme {u.scheme!r} "
              f"(endpoint {endpoint!r})")
        check(bool(u.hostname), f"objstore http: no host in "
                                f"{endpoint!r}")
        self.endpoint = endpoint
        self._scheme = u.scheme
        self._host = u.hostname
        self._port = u.port
        self._base = u.path.rstrip("/")
        self._auth = auth
        self.timeout_s = float(timeout_s)
        if encoded:
            # capability is per-instance: fs.py probes hasattr(client,
            # "get_encoded"), so only an endpoint KNOWN to speak the
            # dtpc transfer coding exposes the method
            self.get_encoded = self._get_encoded
        if multipart:
            # same gate for the write plane: the MultipartWriter probes
            # hasattr(client, "create_multipart"); a plain endpoint
            # without the dmlc upload convention stays single-shot
            self.create_multipart = self._create_multipart
            self.put_part = self._put_part
            self.complete_multipart = self._complete_multipart
            self.abort_multipart = self._abort_multipart
            self.list_uploads = self._list_uploads

    # -- plumbing

    def _headers(self) -> Dict[str, str]:
        a = self._auth
        if a is None:
            return {}
        return dict(a() if callable(a) else a)

    def _path(self, bucket: str, key: str = "",
              query: str = "") -> str:
        check(bucket and "/" not in bucket and ".." not in bucket,
              f"objstore http: invalid bucket {bucket!r}")
        check(".." not in key.split("/"),
              f"objstore http: invalid key {key!r}")
        p = f"{self._base}/{quote(bucket)}"
        if key:
            p += "/" + quote(key)
        if query:
            p += "?" + query
        return p

    def _request(self, method: str, path: str,
                 headers: Optional[Dict[str, str]] = None,
                 body: Optional[bytes] = None
                 ) -> Tuple[int, Dict[str, str], bytes]:
        """One request on a fresh connection (parallel span GETs each
        own theirs — no shared-socket state to corrupt on retry). The
        body is length-checked against ``Content-Length``: a torn
        transfer raises here, inside the caller's retry seam.

        Trace propagation (obs.rpc): when the calling thread holds an
        open client span (the io.objstore.* seams open one per
        attempt) its context rides out as the trace header and the
        server's handle-time echo is folded back in. A thread WITHOUT
        one — a multipart part upload on a pool thread — opens its own
        standalone span so every wire hop stays attributable. With
        tracing off both branches cost one global read."""
        import contextlib as _ctx

        from dmlc_tpu.obs import rpc as _rpc
        conn_cls = (http.client.HTTPSConnection
                    if self._scheme == "https"
                    else http.client.HTTPConnection)
        conn = conn_cls(self._host, self._port, timeout=self.timeout_s)
        with _ctx.ExitStack() as stack:
            stack.callback(conn.close)
            call = _rpc.active_call()
            if call is None:
                call = stack.enter_context(_rpc.client_span(
                    method.lower(), f"{self._host}:{self._port}"))
            hdrs = self._headers()
            if headers:
                hdrs.update(headers)
            if call is not None:
                _rpc.inject(call.ctx, hdrs)
            try:
                conn.request(method, path, body=body, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
            except http.client.HTTPException as e:
                # protocol-layer trouble (IncompleteRead on a torn
                # body, BadStatusLine from a dying server) is
                # TRANSIENT: surface as IOError so the io.objstore.*
                # retry seams classify and re-fetch it
                raise IOError(
                    f"objstore http: {method} {path} failed mid-"
                    f"transfer: {e!r}") from e
            if call is not None:
                echo = resp.headers.get(_rpc.HANDLE_HEADER)
                if echo is not None:
                    call.note_server(echo)
            declared = resp.headers.get("Content-Length")
            if (method != "HEAD" and declared is not None
                    and declared.isdigit()
                    and len(data) != int(declared)):
                raise IOError(
                    f"objstore http: torn {method} {path}: read "
                    f"{len(data)} of Content-Length {declared}")
            return resp.status, dict(resp.headers.items()), data

    @staticmethod
    def _raise_status(status: int, what: str) -> None:
        if status == 404:
            raise FileNotFoundError(f"objstore http: no object "
                                    f"({what})")
        raise IOError(f"objstore http: {what} -> HTTP {status}")

    def _note_range_ignored(self) -> None:
        """A 200 to a ranged GET: correct (we slice locally) but each
        block fetch re-transfers the WHOLE object — an operator must
        hear about the N× wire cost, not discover it in a bill."""
        from dmlc_tpu.obs.log import warn_limited
        warn_limited(
            "objstore-http-range-ignored",
            f"objstore http: endpoint {self.endpoint} ignores Range "
            "— every block fetch transfers the whole object and is "
            "sliced locally. Front the store with a range-capable "
            "gateway (or raise block_bytes/coalesce toward the "
            "object size).",
            min_interval_s=300.0, all_ranks=True)

    # -- client protocol

    def get(self, bucket: str, key: str, start: int = 0,
            end: Optional[int] = None) -> bytes:
        """Ranged GET: bytes ``[start, end)`` (``end`` None = to the
        object's end)."""
        check(start >= 0, "objstore http: negative range start")
        if end is not None and end <= start:
            return b""
        rng = (f"bytes={start}-{end - 1}" if end is not None
               else f"bytes={start}-")
        status, _, data = self._request(
            "GET", self._path(bucket, key), headers={"Range": rng})
        if status == 206:
            return data
        if status == 200:
            # the server ignored Range and sent the whole object:
            # slice locally so callers still get exact range bytes
            if start or end is not None:
                self._note_range_ignored()
            return data[start:end if end is not None else len(data)]
        if status == 416:
            raise DMLCError(f"objstore http: bad range [{start}, "
                            f"{end}) for {bucket}/{key}")
        self._raise_status(status, f"GET {bucket}/{key}")

    def _get_encoded(self, bucket: str, key: str, start: int, end: int,
                     level: int) -> bytes:
        """Ranged GET with the dtpc transfer coding (see module
        docstring). Always returns bytes :func:`decode_page` handles
        unambiguously."""
        from dmlc_tpu.io.codec import decode_page, encode_page
        rng = f"bytes={start}-{end - 1}"
        status, headers, data = self._request(
            "GET", self._path(bucket, key),
            headers={"Range": rng, "X-Dmlc-Accept-Codec": "dtpc",
                     "X-Dmlc-Codec-Level": str(int(level))})
        if status in (200, 206):
            if headers.get("X-Dmlc-Codec") == "dtpc":
                if status == 200:
                    # a Range-ignoring server encoded the WHOLE
                    # object: decode and slice locally like the plain
                    # path, re-wrapped so the caller's decode stays
                    # exact (a torn frame is transient — IOError, so
                    # the io.objstore.get seam re-fetches)
                    self._note_range_ignored()
                    try:
                        data = decode_page(data)[start:end]
                    except DMLCError as e:
                        raise IOError(
                            f"objstore http: corrupt encoded reply "
                            f"for {bucket}/{key}: {e}") from e
                    return encode_page(data, 0)
                return data
            if status == 200:
                self._note_range_ignored()
                data = data[start:end]
            # plain reply: wrap (level 0 only frames magic-prefixed
            # payloads) so decode_page can never misread raw bytes
            return encode_page(data, 0)
        if status == 416:
            raise DMLCError(f"objstore http: bad range [{start}, "
                            f"{end}) for {bucket}/{key}")
        self._raise_status(status, f"GET(encoded) {bucket}/{key}")

    def head(self, bucket: str, key: str) -> RemoteObjectInfo:
        status, headers, _ = self._request(
            "HEAD", self._path(bucket, key))
        if status != 200:
            self._raise_status(status, f"HEAD {bucket}/{key}")
        size_raw = headers.get("Content-Length", "")
        check(size_raw.isdigit(),
              f"objstore http: HEAD {bucket}/{key} sent no "
              "Content-Length")
        mtime_raw = headers.get("X-Dmlc-Mtime-Ns", "")
        mtime_ns = (int(mtime_raw) if mtime_raw.lstrip("-").isdigit()
                    else _parse_http_date_ns(
                        headers.get("Last-Modified")))
        etag = headers.get("ETag", "").strip('"')
        if not etag and mtime_ns == 0:
            # no change token at all: the derived etag degenerates to
            # "<size>-0", so a SAME-SIZE in-place replacement is
            # invisible to the hydration-generation machinery (stale
            # pages would replay as current). Warn loudly — the fix is
            # an ETag- or Last-Modified-speaking endpoint, or
            # versioned object keys.
            from dmlc_tpu.obs.log import warn_limited
            warn_limited(
                "objstore-http-no-change-token",
                f"objstore http: {self.endpoint}/{bucket}/{key} sent "
                "neither ETag nor a parseable Last-Modified — change "
                "detection degrades to object SIZE only; a same-size "
                "replacement will serve stale hydrated pages. Use an "
                "endpoint with change tokens or versioned keys.",
                min_interval_s=300.0, all_ranks=True)
        return RemoteObjectInfo(
            key=key, size=int(size_raw), mtime_ns=mtime_ns, etag=etag)

    def put(self, bucket: str, key: str,
            data: bytes) -> RemoteObjectInfo:
        status, _, _ = self._request(
            "PUT", self._path(bucket, key), body=bytes(data),
            headers={"Content-Type": "application/octet-stream"})
        if status not in (200, 201, 204):
            self._raise_status(status, f"PUT {bucket}/{key}")
        return self.head(bucket, key)

    def put_file(self, bucket: str, key: str,
                 src_path: str) -> RemoteObjectInfo:
        """Upload a local file (bench/test corpus loader — the
        emulator helper's shape)."""
        from dmlc_tpu.io.stream import create_stream
        with create_stream(src_path, "r") as s:
            return self.put(bucket, key, s.read_all())

    def delete(self, bucket: str, key: str) -> bool:
        """Remove one object; True when it existed."""
        status, _, _ = self._request(
            "DELETE", self._path(bucket, key))
        if status == 404:
            return False
        if status not in (200, 202, 204):
            self._raise_status(status, f"DELETE {bucket}/{key}")
        return True

    # -- multipart upload (exposed only with multipart=True)

    def _create_multipart(self, bucket: str, key: str) -> str:
        """Open an upload. The id is minted client-side (no round
        trip): ``p<pid>-<nonce>``, pid-embedded so the sweep's
        liveness rule applies to orphans."""
        import os as _os
        self._path(bucket, key)  # validate bucket/key
        return f"p{_os.getpid()}-{_os.urandom(4).hex()}"

    def _put_part(self, bucket: str, key: str, upload_id: str,
                  part_num: int, data: bytes) -> None:
        check(part_num >= 0, "objstore http: negative part number")
        status, _, _ = self._request(
            "PUT",
            self._path(bucket, key,
                       query=f"dmlc-upload={quote(upload_id)}"
                             f"&dmlc-part={int(part_num)}"),
            body=bytes(data),
            headers={"Content-Type": "application/octet-stream"})
        if status not in (200, 201, 204):
            self._raise_status(
                status, f"PUT part {part_num} {bucket}/{key}")

    def _complete_multipart(self, bucket: str, key: str,
                            upload_id: str,
                            nparts: int) -> RemoteObjectInfo:
        status, _, _ = self._request(
            "POST",
            self._path(bucket, key,
                       query=f"dmlc-upload={quote(upload_id)}"
                             f"&dmlc-complete={int(nparts)}"))
        if status == 404:
            # a part went missing server-side: the upload is torn, not
            # transient — complete can never succeed, the caller aborts
            raise FileNotFoundError(
                f"objstore http: multipart {bucket}/{key} upload "
                f"{upload_id} has missing parts")
        if status not in (200, 201, 204):
            self._raise_status(status, f"COMPLETE {bucket}/{key}")
        return self.head(bucket, key)

    def _abort_multipart(self, bucket: str, key: str,
                         upload_id: str) -> None:
        status, _, _ = self._request(
            "POST",
            self._path(bucket, key,
                       query=f"dmlc-upload={quote(upload_id)}"
                             "&dmlc-abort=1"))
        if status not in (200, 204, 404):  # 404 = already gone: fine
            self._raise_status(status, f"ABORT {bucket}/{key}")

    def _list_uploads(self, bucket: str) -> List[Tuple[str, str]]:
        """In-flight uploads as ``(upload_id, key)`` via ``GET
        <bucket>?dmlc-uploads=1`` (JSON array of pairs)."""
        status, _, data = self._request(
            "GET", self._path(bucket, query="dmlc-uploads=1"))
        if status != 200:
            raise DMLCError(
                f"objstore http: endpoint has no dmlc-uploads support "
                f"for {bucket!r} (HTTP {status})")
        try:
            return [(str(u), str(k))
                    for u, k in json.loads(data.decode("utf-8"))]
        except (ValueError, TypeError) as e:
            raise DMLCError(
                f"objstore http: malformed dmlc-uploads reply for "
                f"{bucket!r}: {e}") from e

    def list(self, bucket: str, prefix: str = ""
             ) -> List[RemoteObjectInfo]:
        """Objects under ``prefix``, key-sorted — via the dmlc listing
        convention (JSON array at ``?dmlc-list=<prefix>``). Endpoints
        without it raise DMLCError: single-object reads never list."""
        status, _, data = self._request(
            "GET", self._path(bucket,
                              query=f"dmlc-list={quote(prefix)}"))
        if status != 200:
            raise DMLCError(
                f"objstore http: endpoint has no dmlc-list support "
                f"for {bucket!r} (HTTP {status}) — pass single-object "
                "URIs, or front the store with a dmlc-aware gateway")
        try:
            rows = json.loads(data.decode("utf-8"))
            out = [RemoteObjectInfo(key=r["key"], size=int(r["size"]),
                                    mtime_ns=int(r.get("mtime_ns", 0)),
                                    etag=str(r.get("etag", "")))
                   for r in rows]
        except (ValueError, KeyError, TypeError) as e:
            raise DMLCError(
                f"objstore http: malformed dmlc-list reply for "
                f"{bucket!r}: {e}") from e
        out.sort(key=lambda o: o.key)
        return out

    def is_prefix(self, bucket: str, key: str = "") -> bool:
        try:
            listing = self.list(bucket, key)
        except DMLCError:
            return False
        prefix = key.rstrip("/") + "/" if key else ""
        return any(o.key.startswith(prefix) and o.key != key
                   for o in listing)
