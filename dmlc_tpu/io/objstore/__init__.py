"""Remote object-store I/O plane: ``obj://`` URIs hydrating the
unified page store.

Reference: PAPER.md §1 "portable streams and virtual filesystems" —
upstream dmlc-core ships S3/HDFS/Azure backends behind one
``FileSystem`` interface. This package is that plane for the TPU
framework: :class:`~dmlc_tpu.io.objstore.fs.ObjectStoreFileSystem`
registered for ``obj://`` (with an ``s3://`` alias) in the existing
scheme registry, reading through ranged parallel GETs with request
coalescing and hydrating fetched blocks into
:mod:`dmlc_tpu.io.pagestore` — so a second epoch over the same remote
URI never touches the wire. The backend is a pluggable client
protocol; this build ships the on-disk
:class:`~dmlc_tpu.io.objstore.emulator.EmulatedObjectStore` (no
network in this container — SURVEY §7), which is also the chaos/bench
harness. See docs/remote_io.md.

    from dmlc_tpu.io import objstore
    em = objstore.configure(root="/tmp/objstore")   # emulator backend
    em.put("bucket", "train/data.libsvm", payload)
    Pipeline.from_uri("obj://bucket/train/data.libsvm").parse(
        format="libsvm")...
"""

from dmlc_tpu.io.filesys import FileSystem
from dmlc_tpu.io.objstore import peer
from dmlc_tpu.io.objstore.emulator import EmulatedObjectStore, ObjectInfo
from dmlc_tpu.io.objstore.fs import (
    ENV_AUTH, ENV_ENDPOINT, ENV_GBPS, ENV_LATENCY, ENV_ROOT,
    ObjectSeekStream, ObjectStoreFileSystem, client, configure, options,
)

# NOTE: http_client (the real networked ranged-GET client) is
# import-optional by design — configure(endpoint=...) loads it lazily;
# importing this package must not pull the wire stack in.

__all__ = [
    "ObjectStoreFileSystem", "ObjectSeekStream", "EmulatedObjectStore",
    "ObjectInfo", "configure", "client", "options", "peer",
    "ENV_ROOT", "ENV_LATENCY", "ENV_GBPS", "ENV_ENDPOINT", "ENV_AUTH",
]

# register the schemes: obj:// is the canonical name, s3:// aliases to
# the same plane (replacing filesys.py's no-backend stub so S3-shaped
# URIs reach the emulator/client instead of an immediate error)
FileSystem.register_scheme("obj://",
                           lambda: ObjectStoreFileSystem("obj://"))
FileSystem.register_scheme("s3://",
                           lambda: ObjectStoreFileSystem("s3://"))
