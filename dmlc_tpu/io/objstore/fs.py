"""obj:// (and s3:// alias) FileSystem: ranged parallel GETs, request
coalescing, and page-store hydration.

Reference: src/io/s3_filesys.cc — upstream's S3 backend behind the one
``FileSystem`` interface (CURL + HMAC there; a pluggable client
protocol here, served by the on-disk emulator in this build — see
emulator.py and SURVEY §7 for why no real wire exists in this
container). The FileSystem surface is exactly the local one's, so
``InputSplit``/parsers/``create_stream`` work over ``obj://`` URIs
unmodified.

Read path (:class:`ObjectSeekStream`):

- the object is addressed in fixed ``block_bytes`` blocks;
- a block miss first consults the unified page store
  (:mod:`dmlc_tpu.io.pagestore`): hydrated blocks are ordinary local
  pages, so a SECOND epoch over the same object performs ZERO wire
  GETs (the acceptance the ``dmlc_objstore_*``/``dmlc_pagestore_*``
  counters prove);
- on a store miss the stream COALESCES the run of missing blocks ahead
  (up to ``coalesce`` blocks) into one byte span and fetches it with up
  to ``parallel`` concurrent ranged GETs — small adjacent reads become
  few large requests, large spans keep the wire full;
- every wire call runs under ``resilience.guarded()`` at the
  ``io.objstore.get`` / ``io.objstore.stat`` / ``io.objstore.list`` /
  ``io.objstore.put`` sites: transient errors retry under policy, an
  armed FaultPlan injects there, and a truncated GET (chaos or a real
  short object) is DETECTED against the requested range and retried —
  never silently passed downstream;
- wire traffic is counted (``objstore.get``, ``objstore.bytes``,
  rendered ``dmlc_objstore_*_total``) and hydration hits/misses ride
  the page-store counters;
- with a page-codec level (``configure(codec_level=N)`` or the
  ``DMLC_TPU_PAGE_CODEC_LEVEL`` process default) ranges travel
  COMPRESSED (``get_encoded`` transfer coding, decoded inside the
  retry seam) and hydrated blocks are stored as codec frames (the
  sidecar stamps which): ``objstore.bytes`` counts compressed on-wire
  bytes, ``objstore.bytes_served`` the decompressed payload — see
  docs/remote_io.md "Page compression" for when the trade pays.

Two tiers sit AHEAD of the wire (ROADMAP item 5, the gang-scale data
plane):

- **gang peers** (:mod:`dmlc_tpu.io.objstore.peer`): in a gang whose
  ranks run the StatusServer (``launch_local(serve_ports=...)``),
  hydration groups — contiguous runs of ``coalesce`` blocks — are
  OWNED round-robin by rank, the owner fetches its groups from the
  wire, and every other rank asks the owner's ``/pages/<entry>``
  endpoint first (fingerprint- and length-validated, decoded, under
  the ``io.objstore.peer`` resilience seam). A cold N-rank epoch moves
  ~1/N of the single-rank wire bytes; any peer trouble degrades to
  the wire, never to corruption or a hang;
- **singleflight** (process-local): concurrent misses of the same
  hydration group dedup onto ONE fetch — the leader fills the store,
  waiters read the committed page (``pagestore.singleflight.lead`` /
  ``pagestore.singleflight.dedup`` counters make the dedup
  auditable). A waiter whose block the leader's span did not cover
  simply fetches it itself.

Hydrated entries are stamped with the object's ``[uri, size, mtime]``
fingerprint AND keyed by its etag: a changed object changes the key
(stale blocks are never served) and the stale sweep reclaims the old
generation's pages.

The wire client is pluggable: the on-disk emulator (tests/bench), or
the REAL networked HTTP ranged-GET client
(:mod:`dmlc_tpu.io.objstore.http_client`, import-optional — built only
when ``configure(endpoint=...)`` / ``DMLC_TPU_OBJSTORE_ENDPOINT``
names one).
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import List, Optional, Tuple

from dmlc_tpu.io.filesys import FileInfo, FileSystem, URI
from dmlc_tpu.io.pagestore import PageStore
from dmlc_tpu.io.stream import MemoryStream, SeekStream, Stream
from dmlc_tpu.obs import rpc as _rpc
from dmlc_tpu.resilience import inject as _inject
from dmlc_tpu.resilience.policy import guarded
from dmlc_tpu.utils.logging import DMLCError, check

__all__ = [
    "ObjectStoreFileSystem", "ObjectSeekStream", "configure", "client",
    "options", "ENV_ROOT", "ENV_LATENCY", "ENV_GBPS", "ENV_ENDPOINT",
    "ENV_AUTH",
]

def _rpc_peer(c) -> str:
    """Edge-table peer label for a backing client: the HTTP endpoint
    when there is one, the emulator otherwise."""
    return getattr(c, "endpoint", None) or "emulator"


ENV_ROOT = "DMLC_TPU_OBJSTORE_ROOT"
ENV_LATENCY = "DMLC_TPU_OBJSTORE_LATENCY_S"
ENV_GBPS = "DMLC_TPU_OBJSTORE_GBPS"
ENV_ENDPOINT = "DMLC_TPU_OBJSTORE_ENDPOINT"
ENV_AUTH = "DMLC_TPU_OBJSTORE_AUTH"  # "Header-Name: value" static auth

_lock = threading.Lock()
_client = None
_options = {
    "block_bytes": 4 << 20,   # hydration/GET granularity
    "coalesce": 4,            # max adjacent missing blocks per span
                              # (ALSO the peer tier's ownership-group
                              # size, so owned wire fetches coalesce)
    "parallel": 4,            # concurrent ranged GETs per span
    "hydrate": True,          # write fetched blocks into the PageStore
    "peer": True,             # consult gang peers (when a tier exists)
                              # before the wire
    "codec_level": None,      # io.codec level for the wire + hydrated
                              # pages; None = the process default
                              # (DMLC_TPU_PAGE_CODEC_LEVEL), 0 = raw
    "put_part_bytes": 8 << 20,  # write streams spill into a multipart
                              # upload once this many bytes buffer
                              # (client permitting); smaller objects
                              # stay single-shot PUTs
    "put_parallel": 4,        # concurrent part uploads per writer
}


_KEEP = object()  # configure() default: tune options, keep the client


def configure(client_obj=_KEEP, *, root: Optional[str] = None,
              endpoint: Optional[str] = None,
              auth=None,
              latency_s: float = 0.0,
              bandwidth_gbps: Optional[float] = None,
              block_bytes: Optional[int] = None,
              coalesce: Optional[int] = None,
              parallel: Optional[int] = None,
              hydrate: Optional[bool] = None,
              peer: Optional[bool] = None,
              codec_level: Optional[int] = None,
              put_part_bytes: Optional[int] = None,
              put_parallel: Optional[int] = None):
    """Install the process's object-store client and tune the read
    path. Returns the installed client. The client is, in order:
    ``client_obj`` verbatim; an
    :class:`~dmlc_tpu.io.objstore.emulator.EmulatedObjectStore` over
    ``root``; the real networked
    :class:`~dmlc_tpu.io.objstore.http_client.HttpObjectStoreClient`
    over ``endpoint`` (``auth`` = static header dict or a callable
    returning one, the auth-header hook). An explicit
    ``configure(None)`` with neither uninstalls; calling with only
    option kwargs (e.g. ``configure(hydrate=False)``) tunes the read
    path without touching the installed client."""
    global _client
    with _lock:
        if client_obj is _KEEP and root is None and endpoint is None:
            client_obj = _client
        elif client_obj is None or client_obj is _KEEP:
            if root is not None:
                from dmlc_tpu.io.objstore.emulator import (
                    EmulatedObjectStore,
                )
                client_obj = EmulatedObjectStore(
                    root, latency_s=latency_s,
                    bandwidth_gbps=bandwidth_gbps)
            elif endpoint is not None:
                # import-optional: the real wire client loads only
                # when an endpoint names one (the emulator stays the
                # test backend)
                from dmlc_tpu.io.objstore.http_client import (
                    HttpObjectStoreClient,
                )
                client_obj = HttpObjectStoreClient(endpoint, auth=auth)
            else:
                client_obj = None  # explicit uninstall
        _client = client_obj
        for key, val in (("block_bytes", block_bytes),
                         ("coalesce", coalesce),
                         ("parallel", parallel),
                         ("hydrate", hydrate),
                         ("peer", peer),
                         ("codec_level", codec_level),
                         ("put_part_bytes", put_part_bytes),
                         ("put_parallel", put_parallel)):
            if val is not None:
                _options[key] = val
        check(_options["block_bytes"] >= 1, "block_bytes must be >= 1")
        check(_options["coalesce"] >= 1, "coalesce must be >= 1")
        check(_options["parallel"] >= 1, "parallel must be >= 1")
        check(_options["put_part_bytes"] >= 1,
              "put_part_bytes must be >= 1")
        check(_options["put_parallel"] >= 1, "put_parallel must be >= 1")
    return _client


def client():
    """The configured client; falls back to the ``DMLC_TPU_OBJSTORE_*``
    env contract — an emulator over ``DMLC_TPU_OBJSTORE_ROOT``, else
    the real HTTP client over ``DMLC_TPU_OBJSTORE_ENDPOINT`` (with an
    optional ``DMLC_TPU_OBJSTORE_AUTH="Header: value"`` static auth
    header) — so gang workers inherit the launcher's store with zero
    code. None when nothing is configured."""
    global _client
    with _lock:
        if _client is not None:
            return _client
    root = os.environ.get(ENV_ROOT)
    if root:
        return configure(
            root=root,
            latency_s=float(os.environ.get(ENV_LATENCY, "0") or "0"),
            bandwidth_gbps=(float(os.environ[ENV_GBPS])
                            if os.environ.get(ENV_GBPS) else None))
    endpoint = os.environ.get(ENV_ENDPOINT)
    if endpoint:
        auth = None
        raw = os.environ.get(ENV_AUTH)
        if raw:
            # fail FAST on a malformed value: silently dropping it
            # would send unauthenticated requests and surface only as
            # baffling 403s from the endpoint
            check(":" in raw,
                  f"{ENV_AUTH} must be 'Header-Name: value', got "
                  f"{raw!r}")
            name, _, value = raw.partition(":")
            auth = {name.strip(): value.strip()}
        return configure(endpoint=endpoint, auth=auth)
    return None


def options() -> dict:
    with _lock:
        return dict(_options)


def _count(which: str, n: int = 1) -> None:
    try:
        from dmlc_tpu.obs.metrics import REGISTRY
        REGISTRY.counter(f"objstore.{which}").inc(n)
    except Exception:  # noqa: BLE001 — telemetry must not break I/O
        pass


def _count_sf(which: str) -> None:
    try:
        from dmlc_tpu.obs.metrics import REGISTRY
        REGISTRY.counter(f"pagestore.singleflight.{which}").inc()
    except Exception:  # noqa: BLE001 — telemetry must not break I/O
        pass


def _bucket_key(uri: URI) -> Tuple[str, str]:
    return uri.host, uri.name.lstrip("/")


class _Singleflight:
    """Process-local hydration dedup: concurrent misses of one
    hydration group elect ONE leader whose fetch fills the page store;
    the waiters then read the committed page instead of issuing their
    own GETs. Bounded wait (a crashed leader's followers proceed on
    their own after ``wait_s``) — dedup is an optimization, never a
    correctness dependency."""

    def __init__(self, wait_s: float = 120.0):
        self.wait_s = wait_s
        self._lock = threading.Lock()
        self._inflight: dict = {}

    def lead(self, key) -> bool:
        """True: caller is the leader (MUST call :meth:`done`).
        False: another thread led; its fetch has completed (or the
        bounded wait expired) by the time this returns."""
        with self._lock:
            ev = self._inflight.get(key)
            if ev is None:
                self._inflight[key] = threading.Event()
                return True
        ev.wait(self.wait_s)
        return False

    def done(self, key) -> None:
        with self._lock:
            ev = self._inflight.pop(key, None)
        if ev is not None:
            ev.set()


_SINGLEFLIGHT = _Singleflight()


class ObjectSeekStream(SeekStream):
    """SeekStream over one remote object; see the module docstring for
    the block/coalesce/parallel/hydrate read path."""

    def __init__(self, client_obj, protocol: str, bucket: str, key: str,
                 size: int, etag: str, mtime_ns: int,
                 opts: Optional[dict] = None,
                 store: Optional[PageStore] = None):
        opts = opts or options()
        self._c = client_obj
        self._bucket = bucket
        self._key = key
        self.size = int(size)
        self.path = f"{protocol}{bucket}/{key}"
        self._bb = int(opts["block_bytes"])
        self._coalesce = int(opts["coalesce"])
        self._parallel = int(opts["parallel"])
        # page/wire codec: None falls back to the process default
        # (DMLC_TPU_PAGE_CODEC_LEVEL); >0 requests transfer-encoded
        # GETs (client permitting) and stores hydrated blocks encoded
        from dmlc_tpu.io import codec as _codec_mod
        lvl = opts.get("codec_level")
        self._codec_level = (_codec_mod.default_level() if lvl is None
                             else int(lvl))
        self._store = (store if store is not None
                       else (PageStore.default() if opts["hydrate"]
                             else None))
        # the gang peer tier (None outside a gang or when peer=False):
        # hydration groups of `coalesce` blocks are owned round-robin
        # by rank; non-owners ask the owner's /pages endpoint first
        self._peer = None
        if opts.get("peer", True):
            from dmlc_tpu.io.objstore import peer as _peer_mod
            t = _peer_mod.tier()
            if t is not None and t.remote_count > 0:
                self._peer = t
        self._group = max(1, self._coalesce)
        # entry names carry the object identity AND its etag: a changed
        # object hydrates a fresh generation, never mixes with the old
        oh = hashlib.sha256(self.path.encode()).hexdigest()[:16]
        eh = hashlib.sha256(str(etag).encode()).hexdigest()[:8]
        self._entry_prefix = f"obj-{oh}-{eh}"
        self._fingerprint = [[self.path, self.size, int(mtime_ns)]]
        self._pos = 0
        self._cur_ix = -1
        self._cur = b""
        self._pool = None

    # -- SeekStream

    def seek(self, pos: int) -> None:
        check(0 <= pos <= self.size,
              f"objstore seek {pos} out of range [0, {self.size}]")
        self._pos = pos

    def tell(self) -> int:
        return self._pos

    def read(self, nbytes: int) -> bytes:
        if nbytes <= 0 or self._pos >= self.size:
            return b""
        ix = self._pos // self._bb
        off = self._pos - ix * self._bb
        buf = self._block(ix)
        out = buf[off:off + nbytes]
        self._pos += len(out)
        return out

    def write(self, data) -> int:
        raise DMLCError("objstore: read-only stream (write via "
                        "FileSystem.open(uri, 'w'))")

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    # -- block plane

    def _nblocks(self) -> int:
        return (self.size + self._bb - 1) // self._bb

    def _entry(self, ix: int) -> str:
        return f"{self._entry_prefix}.b{ix}.pages"

    def _expected(self, ix: int) -> int:
        return min(self.size, (ix + 1) * self._bb) - ix * self._bb

    def _block(self, ix: int) -> bytes:
        if ix == self._cur_ix:
            return self._cur
        data = self._store_block(ix)
        if data is None:
            data = self._fetch_missing(ix)
        self._cur_ix, self._cur = ix, data
        return data

    def _store_block(self, ix: int) -> Optional[bytes]:
        """Block ``ix`` from the page store, decoded and
        length-validated; None on a miss (a torn/foreign page is
        deleted and reported as a miss — refetch, never serve it)."""
        from dmlc_tpu.io.codec import decode_page
        if self._store is None:
            return None
        s = self._store.open_read(self._entry(ix))
        if s is None:
            return None
        with s:
            data = s.read_all()
        try:
            # hydrated entries may be codec-framed (the sidecar
            # stamps which); raw legacy pages pass through
            data = decode_page(data)
        except DMLCError:
            data = b""  # corrupt frame: treat as torn below
        if len(data) != self._expected(ix):
            self._store.delete(self._entry(ix))
            return None
        return data

    def _fetch_missing(self, ix: int) -> bytes:
        """A store miss: singleflight the hydration group, then
        peer-or-wire. The leader's fetch commits the span; followers
        read the committed pages (one GET fills the gang member's
        store for every concurrent reader)."""
        if self._store is None:
            # nothing to dedup INTO — every reader fetches its own
            return self._peer_or_wire(ix)
        key = (self._entry_prefix, self._bb, ix // self._group)
        if _SINGLEFLIGHT.lead(key):
            _count_sf("lead")
            try:
                return self._peer_or_wire(ix)
            finally:
                _SINGLEFLIGHT.done(key)
        _count_sf("dedup")
        data = self._store_block(ix)
        if data is not None:
            return data
        # the leader's span stopped short of our block (or its commit
        # failed): fetch it ourselves
        return self._peer_or_wire(ix)

    def _peer_or_wire(self, ix: int) -> bytes:
        """The tiered fetch for one missing block: gang peer (when the
        group is owned by another rank) ahead of the object store."""
        tier = self._peer
        if tier is None:
            return self._fetch_span(ix)
        group_ix = ix // self._group
        owner = tier.owner_index(group_ix)
        if owner is None:
            # self-owned: fetch from the wire, clamped to OUR group so
            # the coalesced span never pre-fetches a peer-owned block
            end_of_group = (group_ix + 1) * self._group
            return self._fetch_span(ix, limit_blocks=end_of_group - ix)
        data = tier.fetch_entry(owner, self._entry(ix),
                                self._fingerprint, self._expected(ix))
        if data is not None:
            if self._store is not None:
                self._hydrate(ix, data)
            return data
        if tier.available(owner):
            # the owner is alive but behind (or served a bad page):
            # take just this block from the wire — the owner will
            # still serve the rest of its group
            return self._fetch_span(ix, limit_blocks=1)
        # breaker open (dead peer): its groups are ours now, full
        # coalesced spans and all
        return self._fetch_span(ix)

    def _fetch_span(self, ix: int,
                    limit_blocks: Optional[int] = None) -> bytes:
        """Fetch the run of store-missing blocks starting at ``ix``
        (request coalescing, capped at ``limit_blocks`` when the peer
        tier bounds the span to an ownership group), as up to
        ``parallel`` concurrent ranged GETs; hydrate every fetched
        block. Returns block ``ix``."""
        span_blocks = self._coalesce
        if limit_blocks is not None:
            span_blocks = max(1, min(span_blocks, limit_blocks))
        last = min(ix + span_blocks, self._nblocks())
        j = ix + 1
        while j < last and not (self._store is not None
                                and self._store.exists(self._entry(j))):
            j += 1
        start, end = ix * self._bb, min(j * self._bb, self.size)
        nblocks = j - ix
        nway = min(self._parallel, nblocks)
        # block-aligned contiguous sub-ranges, one ranged GET each
        per = (nblocks + nway - 1) // nway
        ranges = []
        b = ix
        while b < j:
            hi = min(b + per, j)
            ranges.append((b * self._bb, min(hi * self._bb, self.size)))
            b = hi
        if len(ranges) == 1:
            datas = [self._get_range(*ranges[0])]
        else:
            datas = list(self._executor().map(
                lambda r: self._get_range(*r), ranges))
        span = b"".join(datas)
        check(len(span) == end - start,
              "objstore: span reassembly mismatch")
        first = b""
        for k in range(ix, j):
            lo = k * self._bb - start
            blk = span[lo:lo + self._expected(k)]
            if k == ix:
                first = blk
            if self._store is not None:
                self._hydrate(k, blk)
        return first

    def _executor(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=self._parallel,
                thread_name_prefix="dmlc_tpu.objstore.get")
        return self._pool

    def _get_range(self, start: int, end: int) -> bytes:
        """One ranged GET under the ``io.objstore.get`` seam. A short
        payload — injected truncation or a really-shrunk object — is
        detected against the requested range and raised as a transient
        IOError, so the site's retry policy re-fetches instead of the
        caller parsing shifted bytes.

        With a codec level (and a client that speaks ``get_encoded``)
        the range travels compressed: the decode runs INSIDE the retry
        seam — a corrupt or truncated wire frame raises and the whole
        GET re-fetches — and the counters stay honest:
        ``objstore.bytes`` counts on-wire (compressed) bytes,
        ``objstore.bytes_served`` the decompressed payload actually
        handed downstream."""
        from dmlc_tpu.io.codec import decode_page
        want = end - start
        encoded = (self._codec_level > 0
                   and hasattr(self._c, "get_encoded"))
        peer = _rpc_peer(self._c)

        def attempt():
            # one client span per ATTEMPT (obs.rpc): the enclosing
            # operation() pins the trace_id, so injected retries show
            # as countable same-trace spans on the timeline
            with _rpc.client_span("get", peer):
                if encoded:
                    wire = self._c.get_encoded(self._bucket, self._key,
                                               start, end,
                                               self._codec_level)
                    wire = _inject.corrupt("io.objstore.get", wire)
                    try:
                        data = decode_page(wire)
                    except DMLCError as e:
                        raise IOError(
                            f"objstore: corrupt encoded GET on "
                            f"{self.path} [{start}, {end}): {e}"
                        ) from e
                else:
                    data = _inject.corrupt(
                        "io.objstore.get",
                        self._c.get(self._bucket, self._key, start,
                                    end))
                    wire = data
                if len(data) != want:
                    raise IOError(
                        f"objstore: short ranged GET on {self.path} "
                        f"[{start}, {end}): got {len(data)}/{want} "
                        "bytes (truncated object or torn transfer)")
                return wire, data

        with _rpc.operation("io.objstore.get", peer=peer):
            wire, data = guarded("io.objstore.get", attempt)
        _count("get")
        _count("bytes", len(wire))
        _count("bytes_served", len(data))
        return data

    def _hydrate(self, ix: int, data: bytes) -> None:
        """Commit a fetched block into the page store (best-effort: a
        full disk degrades to re-fetching, never kills the read). With
        a codec level the entry is stored as a codec frame — fewer NVMe
        bytes per cached block — and the sidecar stamps which codec
        (``"codec"`` in the entry meta)."""
        from dmlc_tpu.io.codec import encode_page, tag
        name = self._entry(ix)
        data = encode_page(data, self._codec_level)
        try:
            w = self._store.writer(
                name, fingerprint=self._fingerprint,
                meta={"block": ix, "codec": tag(self._codec_level)})
            try:
                w.write(data)
            except Exception:
                w.abort()
                raise
            w.commit()
        except Exception as e:  # noqa: BLE001 — cache trouble != I/O failure
            try:
                from dmlc_tpu.obs.log import warn_limited
                warn_limited(
                    "objstore-hydrate-failed",
                    f"objstore: page hydration failed ({e}); reads "
                    "will keep hitting the wire",
                    min_interval_s=60.0, all_ranks=True)
            except Exception:  # noqa: BLE001
                pass


class _ObjectWriteStream(Stream):
    """Write stream over one object. Small objects buffer in RAM and
    land as a single PUT on close (object stores have no append); once
    the buffer crosses ``options()["put_part_bytes"]`` — and the client
    speaks the multipart verbs — the stream spills into a
    :class:`~dmlc_tpu.io.objstore.multipart.MultipartWriter` and the
    rest of the bytes travel as bounded-parallel fixed-size parts. Both
    paths run under the ``io.objstore.put`` seam: transient failures
    retry, a failed upload leaves NO torn object at the key."""

    def __init__(self, client_obj, bucket: str, key: str, path: str,
                 opts: Optional[dict] = None):
        opts = opts or options()
        self._c = client_obj
        self._bucket = bucket
        self._key = key
        self.path = path
        self._part_bytes = int(opts["put_part_bytes"])
        self._put_parallel = int(opts["put_parallel"])
        self._buf: Optional[MemoryStream] = MemoryStream()
        self._mp = None
        self._closed = False

    def _spill(self):
        """Switch to the multipart writer (None when the client does
        not speak the verbs — the stream stays single-shot)."""
        from dmlc_tpu.io.objstore.multipart import (
            MultipartWriter, supports_multipart,
        )
        if not supports_multipart(self._c):
            return None
        self._mp = MultipartWriter(
            self._c, self._bucket, self._key, self.path,
            part_bytes=self._part_bytes, parallel=self._put_parallel)
        return self._mp

    def write(self, data) -> int:
        check(not self._closed, "objstore: write after close")
        if self._mp is not None:
            return self._mp.write(data)
        if self._buf.tell() == 0 and len(data) >= self._part_bytes:
            # a whole part arriving at once: hand it straight to the
            # multipart writer, never staged through the buffer
            mp = self._spill()
            if mp is not None:
                self._buf = None
                return mp.write(data)
        n = self._buf.write(bytes(data))
        if self._buf.tell() >= self._part_bytes and \
                self._spill() is not None:
            self._mp.write(self._buf.getvalue())
            self._buf = None
        return n

    def read(self, nbytes: int) -> bytes:
        raise DMLCError("objstore: write-only stream")

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._mp is not None:
            self._mp.close()
            return
        payload = self._buf.getvalue()
        self._buf = None

        def attempt():
            # the writer owns the bytes: injected truncation (chaos at
            # io.objstore.put) is detected HERE and retried — a torn
            # single-shot PUT never lands short
            with _rpc.client_span("put", _rpc_peer(self._c)):
                data = _inject.corrupt("io.objstore.put", payload)
                if len(data) != len(payload):
                    raise IOError(
                        f"objstore: torn PUT on {self.path}: sent "
                        f"{len(data)}/{len(payload)} bytes")
                self._c.put(self._bucket, self._key, data)

        with _rpc.operation("io.objstore.put",
                            peer=_rpc_peer(self._c)):
            guarded("io.objstore.put", attempt)
        _count("put")
        _count("put.bytes", len(payload))


class ObjectStoreFileSystem(FileSystem):
    """The ``obj://`` scheme (``s3://`` aliases to it); resolves the
    process's configured client lazily so registration at import time
    costs nothing."""

    def __init__(self, protocol: str = "obj://"):
        self.protocol = protocol

    def _client(self):
        c = client()
        if c is None:
            raise DMLCError(
                f"filesystem {self.protocol!r}: no object-store "
                f"endpoint configured. Set {ENV_ROOT}=<dir> for the "
                "on-disk emulator, or call "
                "dmlc_tpu.io.objstore.configure(client_or_root) "
                "(docs/remote_io.md).")
        return c

    def open(self, uri: URI, mode: str) -> Stream:
        check(mode in ("r", "w"),
              f"objstore: mode {mode!r} unsupported (no append on "
              "object stores)")
        if mode == "r":
            return self.open_for_read(uri)
        bucket, key = _bucket_key(uri)
        check(bool(bucket) and bool(key),
              f"objstore: need {self.protocol}bucket/key, got "
              f"{uri.str_uri()!r}")
        return _ObjectWriteStream(self._client(), bucket, key,
                                  uri.str_uri())

    def open_for_read(self, uri: URI) -> ObjectSeekStream:
        c = self._client()
        bucket, key = _bucket_key(uri)

        def attempt():
            with _rpc.client_span("stat", _rpc_peer(c)):
                return c.head(bucket, key)

        with _rpc.operation("io.objstore.stat", peer=_rpc_peer(c)):
            info = guarded("io.objstore.stat", attempt)
        _count("stat")
        return ObjectSeekStream(c, self.protocol, bucket, key,
                                size=info.size, etag=info.etag,
                                mtime_ns=info.mtime_ns)

    def get_path_info(self, uri: URI) -> FileInfo:
        c = self._client()
        bucket, key = _bucket_key(uri)
        path = uri.str_uri()

        def stat() -> FileInfo:
            with _rpc.client_span("stat", _rpc_peer(c)):
                try:
                    info = c.head(bucket, key)
                    return FileInfo(path=path, size=info.size,
                                    type="file",
                                    mtime_ns=info.mtime_ns)
                except FileNotFoundError:
                    if c.is_prefix(bucket, key):
                        return FileInfo(path=path, size=0,
                                        type="directory")
                    raise

        with _rpc.operation("io.objstore.stat", peer=_rpc_peer(c)):
            out = guarded("io.objstore.stat", stat)
        _count("stat")
        return out

    def list_directory(self, uri: URI) -> List[FileInfo]:
        c = self._client()
        bucket, key = _bucket_key(uri)

        def attempt():
            with _rpc.client_span("list", _rpc_peer(c)):
                return c.list(bucket, key)

        with _rpc.operation("io.objstore.list", peer=_rpc_peer(c)):
            infos = guarded("io.objstore.list", attempt)
        _count("list")
        return [FileInfo(path=f"{self.protocol}{bucket}/{o.key}",
                         size=o.size, type="file", mtime_ns=o.mtime_ns)
                for o in infos]
