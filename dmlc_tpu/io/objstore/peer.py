"""Peer hydration tier: gang peers as a ranged-GET backend AHEAD of
the wire.

ROADMAP item 5: today every rank hydrates ``obj://`` pages over its
own wire, so an N-rank gang re-fetches the same bytes N times. This
module is the fix's client half — each rank's
:class:`~dmlc_tpu.obs.serve.StatusServer` (the PR-4 live-telemetry
plane) grows a ``GET /pages/<entry>`` data endpoint serving its
fingerprint-fresh committed page-store entries, and
:class:`~dmlc_tpu.io.objstore.fs.ObjectSeekStream` consults the gang
through a :class:`PeerTier` before falling back to the object store:

- **ownership**: hydration blocks group into contiguous runs of
  ``coalesce`` blocks (the span-coalescing unit, so wire fetches stay
  coalesced), and group ``g`` is OWNED by rank ``g % world``. The
  owner fetches its groups from the wire exactly as before; every
  other rank asks the owner's ``/pages`` endpoint first — so a cold
  gang epoch moves ~1/N of the single-rank wire bytes, each byte
  GET'd once and peer-served N-1 times;
- **the seam**: every peer fetch runs under ``resilience.guarded()``
  at the NEW ``io.objstore.peer`` site — an owner that has not
  hydrated the block yet answers 404, which retries under the site's
  policy (the non-owner paces itself behind the owner) and then
  degrades to the wire. Chaos (``ioerror``/``truncate`` FaultPlans at
  ``io.objstore.peer``) rides the same path: degrade to the wire,
  never corruption, never a hang;
- **validation**: the peer's response carries the entry's stamped
  fingerprint and codec tag; the client decodes the codec frame,
  compares the fingerprint against its OWN ``[uri, size, mtime_ns]``
  expectation, and length-checks the block — a peer serving a
  STALE-fingerprint page is rejected (an IOError inside the seam) and
  the block is refetched from the wire;
- **breaker**: ``breaker_failures`` consecutive degraded fetches from
  one peer snooze it for ``breaker_snooze_s`` — a dead rank costs a
  bounded number of probes, after which its groups fetch as full
  coalesced wire spans again;
- **telemetry**: ``objstore.peer.get`` / ``objstore.peer.bytes`` /
  ``objstore.peer.miss`` (rendered ``dmlc_objstore_peer_*_total``)
  make the dedup auditable next to the serving side's
  ``objstore.peer.served`` / ``objstore.peer.served_bytes``.

Configuration is the gang's existing live-telemetry env contract —
``DMLC_TPU_SERVE_PORTS`` (the gang list, one port per rank in task-id
order) and ``DMLC_TPU_SERVE_PORT`` (this rank's own port), both set by
``launch_local(serve_ports=...)`` — so a gang that serves /metrics is
already a peer data plane. :func:`configure` overrides for tests and
embeddings; :func:`tier` returns the process tier (None when the
process is not in a gang).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional
from urllib.parse import quote
from urllib.request import Request, urlopen

from dmlc_tpu.obs import rpc as _rpc
from dmlc_tpu.resilience import inject as _inject
from dmlc_tpu.resilience.policy import guarded

__all__ = ["PeerTier", "tier", "configure", "FINGERPRINT_HEADER",
           "CODEC_HEADER"]

# response headers the /pages endpoint stamps (obs/serve.py writes
# them, this module validates them — keep in lockstep)
FINGERPRINT_HEADER = "X-Dmlc-Fingerprint"
CODEC_HEADER = "X-Dmlc-Codec"

_lock = threading.Lock()
_tier: Optional["PeerTier"] = None
_tier_built = False


def _count(which: str, n: int = 1) -> None:
    try:
        from dmlc_tpu.obs.metrics import REGISTRY
        REGISTRY.counter(f"objstore.peer.{which}").inc(n)
    except Exception:  # noqa: BLE001 — telemetry must not break I/O
        pass


class PeerTier:
    """The gang's page servers as a read tier. One instance per
    process (see :func:`tier`); thread-safe."""

    def __init__(self, ports: List[int], self_port: Optional[int] = None,
                 host: str = "127.0.0.1", timeout_s: float = 2.0,
                 breaker_failures: int = 3,
                 breaker_snooze_s: float = 5.0):
        self.ports = [int(p) for p in ports]
        self.host = host
        self.timeout_s = float(timeout_s)
        self.self_index: Optional[int] = None
        if self_port is not None and int(self_port) in self.ports:
            self.self_index = self.ports.index(int(self_port))
        self.breaker_failures = int(breaker_failures)
        self.breaker_snooze_s = float(breaker_snooze_s)
        self._lock = threading.Lock()
        self._fails = [0] * len(self.ports)
        self._snoozed_until = [0.0] * len(self.ports)
        self._dead: set = set()

    # -- topology

    @property
    def world(self) -> int:
        return len(self.ports)

    @property
    def remote_count(self) -> int:
        """Peers other than this process — the tier is inert at 0."""
        return self.world - (1 if self.self_index is not None else 0)

    def owner_index(self, group_ix: int) -> Optional[int]:
        """The rank owning hydration group ``group_ix`` (fetches it
        from the wire; everyone else asks its /pages first). None when
        this process IS the owner — including when a DEAD base
        owner's group round-robins onto this process: a rank the
        rendezvous declared dead (:meth:`mark_dead`) costs zero
        probes, its groups reassign deterministically over the
        survivors instead of degrading to full-span wire fetches for
        the rest of the run."""
        if not self.ports:
            return None
        owner = group_ix % self.world
        dead = self._dead
        if owner in dead:
            survivors = [i for i in range(self.world)
                         if i not in dead]
            if not survivors:
                return None
            owner = survivors[group_ix % len(survivors)]
        if owner == self.self_index:
            return None
        return owner

    def mark_dead(self, index: int) -> None:
        """Declare a rank permanently dead (rendezvous roster says
        so, or the supervisor reported it): its page groups reassign
        onto survivors immediately and :meth:`available` answers
        False without burning breaker probes."""
        with self._lock:
            if 0 <= int(index) < self.world:
                self._dead.add(int(index))

    def refresh(self, ports: List[int],
                self_port: Optional[int] = None) -> None:
        """Adopt a new roster IN PLACE (live ObjectSeekStreams hold
        this instance): new port list in rank order, recomputed self
        index, dead set cleared, and the breaker fully reset — the
        3-strike/5s breaker exists for FLAKY peers, and a roster
        change means the flaky/dead topology it learned is stale."""
        with self._lock:
            self.ports = [int(p) for p in ports]
            self.self_index = None
            if self_port is not None and int(self_port) in self.ports:
                self.self_index = self.ports.index(int(self_port))
            self._fails = [0] * len(self.ports)
            self._snoozed_until = [0.0] * len(self.ports)
            self._dead = set()

    # -- breaker

    def available(self, index: int) -> bool:
        """Whether the peer is currently worth asking (breaker not
        open, not declared dead). A snoozed peer's groups fetch as
        full wire spans; a DEAD peer's groups have already been
        reassigned by :meth:`owner_index`."""
        with self._lock:
            if index in self._dead or index >= len(self._fails):
                return False
            if self._fails[index] < self.breaker_failures:
                return True
            return time.monotonic() >= self._snoozed_until[index]

    def _note_failure(self, index: int) -> None:
        with self._lock:
            if index >= len(self._fails):  # refresh() shrank the gang
                return                     # under an in-flight fetch
            self._fails[index] += 1
            if self._fails[index] >= self.breaker_failures:
                self._snoozed_until[index] = (time.monotonic()
                                              + self.breaker_snooze_s)

    def _note_success(self, index: int) -> None:
        with self._lock:
            if index < len(self._fails):
                self._fails[index] = 0

    # -- the fetch

    def fetch_entry(self, index: int, entry: str, fingerprint,
                    expected_len: int) -> Optional[bytes]:
        """One peer-tier block fetch under the ``io.objstore.peer``
        seam. Returns the decoded block bytes, or None — the tier's
        "degrade to the wire" answer (peer missing/behind/unreachable,
        chaos exhausted the site policy, stale fingerprint, torn
        payload). Never raises, never hangs: attempts are bounded by
        the site's retry policy and each carries ``timeout_s``."""
        if index >= len(self.ports) or not self.available(index):
            _count("miss")
            return None
        url = (f"http://{self.host}:{self.ports[index]}"
               f"/pages/{quote(entry, safe='')}")
        peer_label = f"{self.host}:{self.ports[index]}"
        want_fp = [list(e) for e in fingerprint] if fingerprint else None

        def attempt() -> bytes:
            from dmlc_tpu.io.codec import decode_page
            from dmlc_tpu.utils.logging import DMLCError
            with _rpc.client_span("pages", peer_label) as call:
                hdrs = {}
                if call is not None:
                    _rpc.inject(call.ctx, hdrs)
                with urlopen(Request(url, headers=hdrs),
                             timeout=self.timeout_s) as resp:
                    raw = resp.read()
                    got_fp = resp.headers.get(FINGERPRINT_HEADER)
                    if call is not None:
                        call.note_server(
                            resp.headers.get(_rpc.HANDLE_HEADER))
            # chaos: a truncate clause at io.objstore.peer tears the
            # peer payload INSIDE the retried attempt, like the wire
            raw = _inject.corrupt("io.objstore.peer", raw)
            if want_fp is not None:
                try:
                    peer_fp = json.loads(got_fp) if got_fp else None
                except ValueError:
                    peer_fp = None
                if peer_fp != want_fp:
                    # stale or unstamped page: never serve it — the
                    # wire (or a retried fresh peer commit) owns truth
                    raise IOError(
                        f"objstore.peer: stale fingerprint on {entry} "
                        f"(peer {peer_fp!r} != expected {want_fp!r})")
            try:
                data = decode_page(raw)
            except DMLCError as e:
                raise IOError(
                    f"objstore.peer: torn page payload for {entry}: "
                    f"{e}") from e
            if len(data) != expected_len:
                raise IOError(
                    f"objstore.peer: short page {entry}: got "
                    f"{len(data)}/{expected_len} bytes")
            return data

        try:
            with _rpc.operation("io.objstore.peer", peer=peer_label):
                data = guarded("io.objstore.peer", attempt)
        except Exception:  # noqa: BLE001 — ANY failure degrades to wire
            self._note_failure(index)
            _count("miss")
            return None
        self._note_success(index)
        _count("get")
        _count("bytes", len(data))
        return data


def configure(ports: Optional[List[int]] = None,
              self_port: Optional[int] = None,
              host: str = "127.0.0.1",
              timeout_s: float = 2.0,
              breaker_failures: int = 3,
              breaker_snooze_s: float = 5.0,
              enabled: bool = True) -> Optional["PeerTier"]:
    """Install the process peer tier explicitly (tests, embeddings;
    gangs get it free from the env contract). ``enabled=False`` (or
    ``ports=None``) uninstalls — the next :func:`tier` call re-reads
    the env."""
    global _tier, _tier_built
    with _lock:
        if not enabled or ports is None:
            _tier, _tier_built = None, not enabled
            return None
        _tier = PeerTier(ports, self_port=self_port, host=host,
                         timeout_s=timeout_s,
                         breaker_failures=breaker_failures,
                         breaker_snooze_s=breaker_snooze_s)
        _tier_built = True
        return _tier


def reset() -> None:
    """Forget the installed/declined tier (tests); the env is re-read
    on the next :func:`tier` call."""
    global _tier, _tier_built
    with _lock:
        _tier, _tier_built = None, False


def tier() -> Optional["PeerTier"]:
    """The process peer tier: the configured one, else built once from
    the gang env contract (``DMLC_TPU_SERVE_PORTS`` +
    ``DMLC_TPU_SERVE_PORT``); None outside a gang (or when the gang
    has no other member to ask)."""
    global _tier, _tier_built
    with _lock:
        if _tier_built:
            return _tier
        _tier_built = True
        from dmlc_tpu.obs.serve import ENV_SERVE_PORT, ENV_SERVE_PORTS
        raw = os.environ.get(ENV_SERVE_PORTS, "")
        try:
            ports = [int(p) for p in raw.split(",") if p.strip()]
        except ValueError:
            # a mangled gang list must not crash the first obj://
            # read and then silently differ on later ones — warn once,
            # run tierless consistently
            try:
                from dmlc_tpu.obs.log import warn_once
                warn_once("peer-ports-malformed",
                          f"objstore.peer: malformed {ENV_SERVE_PORTS}"
                          f"={raw!r}; peer tier disabled",
                          all_ranks=True)
            except Exception:  # noqa: BLE001
                pass
            return None
        if len(ports) < 2:
            return None
        self_raw = os.environ.get(ENV_SERVE_PORT)
        try:
            self_port = int(self_raw) if self_raw else None
        except ValueError:
            self_port = None
        _tier = PeerTier(ports, self_port=self_port)
        return _tier
