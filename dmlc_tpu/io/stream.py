"""Byte-stream abstraction + URI-dispatched factory.

Reference: include/dmlc/io.h — Stream (Read/Write), SeekStream (Seek/Tell),
Stream::Create(uri, flag, allow_null), SeekStream::CreateForRead,
Serializable, dmlc::istream/ostream adapters; include/dmlc/memory_io.h —
MemoryStringStream/MemoryFixedSizeStream.

Python semantics: ``read(n)`` returns up to ``n`` bytes (b"" at EOF), matching
the reference's size_t-returning Read; ``read_exact``/``write`` helpers carry
the serializer. ``as_file()`` adapts a Stream to a Python file object
(reference: dmlc::istream/ostream).
"""

from __future__ import annotations

import io as _pyio
from typing import Optional, Union

from dmlc_tpu.resilience import inject as _inject
from dmlc_tpu.resilience.policy import guarded
from dmlc_tpu.utils.logging import DMLCError, check

__all__ = [
    "Stream", "SeekStream", "MemoryStream", "Serializable",
    "create_stream", "create_seek_stream_for_read",
]


class Stream:
    """Abstract byte stream (reference: dmlc::Stream)."""

    def read(self, nbytes: int) -> bytes:
        """Read up to nbytes; b"" at EOF."""
        raise NotImplementedError

    def write(self, data: Union[bytes, bytearray, memoryview]) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass

    # -- helpers shared by all streams

    def read_exact(self, nbytes: int) -> bytes:
        """Read exactly nbytes or raise (short read = corrupt stream)."""
        chunks = []
        remaining = nbytes
        while remaining > 0:
            b = self.read(remaining)
            if not b:
                raise DMLCError(
                    f"Stream: unexpected EOF (wanted {nbytes}, "
                    f"got {nbytes - remaining})")
            chunks.append(b)
            remaining -= len(b)
        return b"".join(chunks)

    def read_all(self, chunk_size: int = 1 << 20) -> bytes:
        chunks = []
        while True:
            b = self.read(chunk_size)
            if not b:
                break
            chunks.append(b)
        return b"".join(chunks)

    def readinto(self, b) -> int:
        """Read up to len(b) bytes INTO a caller buffer; returns the
        count (0 at EOF). The base implementation reads-then-copies;
        file-backed streams override with a true in-place read so pooled
        staging buffers skip the fresh-bytes allocation per chunk."""
        data = self.read(len(b))
        n = len(data)
        b[:n] = data
        return n

    def __enter__(self) -> "Stream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def as_file(self, size: Optional[int] = None,
                own_stream: bool = False) -> "_StreamFile":
        """Adapt to a Python binary-file-like object (reference
        dmlc::istream). Pass the object's total ``size`` to enable
        seek-from-end (whence=2) on SeekStreams — consumers like
        pyarrow discover file size that way.

        Ownership (ADVICE r5): by default the adapter does NOT own the
        stream — closing the adapter (or letting it be GC'd;
        RawIOBase.__del__ calls close()) leaves the stream open, so a
        temporary ``s.as_file().write(...)`` cannot close ``s`` out
        from under its owner mid-``with``. Pass ``own_stream=True`` to
        transfer ownership: the adapter then closes the underlying
        stream with itself (the right mode when the adapter is handed
        off, e.g. to pyarrow)."""
        return _StreamFile(self, size=size, own_stream=own_stream)


class SeekStream(Stream):
    """Stream with random access (reference: dmlc::SeekStream)."""

    def seek(self, pos: int) -> None:
        raise NotImplementedError

    def tell(self) -> int:
        raise NotImplementedError


class Serializable:
    """Objects that (de)serialize onto a Stream (reference: dmlc::Serializable)."""

    def save(self, stream: Stream) -> None:
        raise NotImplementedError

    def load(self, stream: Stream) -> None:
        raise NotImplementedError


class MemoryStream(SeekStream):
    """Seekable stream over an in-RAM buffer (reference: MemoryStringStream).

    Construct empty for writing, or over initial bytes for reading. The
    buffer is reachable via :meth:`getvalue`.
    """

    def __init__(self, data: Union[bytes, bytearray, None] = None):
        self._buf = bytearray(data if data is not None else b"")
        self._pos = 0

    def read(self, nbytes: int) -> bytes:
        b = bytes(self._buf[self._pos:self._pos + nbytes])
        self._pos += len(b)
        return b

    def write(self, data) -> int:
        n = len(data)
        end = self._pos + n
        if self._pos == len(self._buf):
            self._buf += bytes(data)
        else:
            if end > len(self._buf):
                self._buf += b"\x00" * (end - len(self._buf))
            self._buf[self._pos:end] = bytes(data)
        self._pos = end
        return n

    def seek(self, pos: int) -> None:
        check(0 <= pos <= len(self._buf), f"seek {pos} out of range")
        self._pos = pos

    def tell(self) -> int:
        return self._pos

    def getvalue(self) -> bytes:
        return bytes(self._buf)

    def __len__(self) -> int:
        return len(self._buf)


class FileStream(SeekStream):
    """Local-file stream over a Python file object (reference:
    src/io/local_filesys.cc FileStream over stdio).

    Reads are a resilience seam (site ``io.stream.read``): transient
    OSErrors retry under the site's RetryPolicy and an armed FaultPlan
    can raise/delay/truncate here. Two position rules keep chaos
    DETECTABLE instead of silently corrupting:

    - a retried attempt SEEKS BACK to the pre-read position first — a
      buffered read that failed after consuming k bytes advances the
      offset, and re-reading from there would return a stream missing
      those bytes (fixed-size payload reads would then load shifted,
      wrong data);
    - an injected truncation shortens the returned bytes AND pins the
      stream at EOF — simulating a truncated SOURCE object whose tail
      is gone, which framing layers surface as an unexpected-EOF
      error. Shortening alone would leave the offset past the dropped
      bytes: the next read would return shifted data and fixed-size
      readers would succeed with silently wrong payloads.

    Unseekable fileobjs (stdin/pipes) fall back to plain re-read and
    skip truncation. The quiet path costs one tell + global read +
    try/except per call."""

    def __init__(self, fileobj, path: str = ""):
        self._f = fileobj
        self.path = path

    def _tell(self):
        try:
            return self._f.tell()
        except OSError:
            return None  # unseekable (stdin/pipe)

    def _restoring(self, pos, fn):
        """Wrap a read op so every RETRY attempt starts at the same
        file position. The first attempt skips the restore (the
        position cannot have moved yet — the quiet path stays at one
        tell per call)."""
        first = [True]

        def attempt():
            if first[0]:
                first[0] = False
            elif pos is not None:
                self._f.seek(pos)
            return fn()

        return attempt

    def _truncated_len(self, pos, nread: int, payload) -> int:
        """Armed truncate clauses: shortened length, stream pinned at
        EOF (see class docstring); ``nread`` when chaos is off. The
        payload is materialized only when a truncate clause is scoped
        here — a plan targeting other sites must not cost the hot
        readinto path a copy per chunk."""
        plan = _inject.active()
        if plan is None or not nread or pos is None \
                or not plan.has_truncate("io.stream.read"):
            return nread
        short = plan.corrupt("io.stream.read", payload())
        if len(short) != nread:
            self._f.seek(0, 2)  # the source's tail is GONE
            return len(short)
        return nread

    def read(self, nbytes: int) -> bytes:
        pos = self._tell()
        out = guarded("io.stream.read",
                      self._restoring(pos, lambda: self._f.read(nbytes)))
        if _inject.active() is not None:
            out = out[:self._truncated_len(pos, len(out), lambda: out)]
        return out

    def readinto(self, b) -> int:
        ri = getattr(self._f, "readinto", None)
        if ri is None:
            return super().readinto(b)  # routes through read() above
        pos = self._tell()
        n = guarded("io.stream.read",
                    self._restoring(pos, lambda: int(ri(b))))
        if _inject.active() is not None:
            n = self._truncated_len(pos, n,
                                    lambda: bytes(memoryview(b)[:n]))
        return n

    def write(self, data) -> int:
        return self._f.write(data)

    def seek(self, pos: int) -> None:
        self._f.seek(pos)

    def tell(self) -> int:
        return self._f.tell()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class _StreamFile(_pyio.RawIOBase):
    """Binary file adapter over a Stream (reference dmlc::istream/ostream)."""

    def __init__(self, stream: Stream, size: Optional[int] = None,
                 own_stream: bool = False):
        self._s = stream
        self._size = size
        self._own = own_stream

    def close(self) -> None:
        # with own_stream, propagate to the underlying Stream (fd/
        # socket/remote handle) — RawIOBase.close() alone would strand
        # it until GC; without it, the stream's owner keeps control
        # (see Stream.as_file)
        try:
            if self._own:
                self._s.close()
        finally:
            super().close()

    def readable(self) -> bool:
        return True

    def writable(self) -> bool:
        return True

    def readinto(self, b) -> int:
        data = self._s.read(len(b))
        b[:len(data)] = data
        return len(data)

    def write(self, b) -> int:
        return self._s.write(bytes(b))

    def seekable(self) -> bool:
        return isinstance(self._s, SeekStream)

    def seek(self, pos, whence=0):
        if not isinstance(self._s, SeekStream):
            raise _pyio.UnsupportedOperation("seek")
        if whence == 0:
            self._s.seek(pos)
        elif whence == 1:
            self._s.seek(self._s.tell() + pos)
        elif whence == 2 and self._size is not None:
            self._s.seek(self._size + pos)
        else:
            raise _pyio.UnsupportedOperation(
                "seek from end needs as_file(size=...)")
        return self._s.tell()


def create_stream(uri: str, mode: str = "r",
                  allow_null: bool = False) -> Optional[Stream]:
    """URI-dispatched stream factory (reference: Stream::Create in src/io.cc).

    mode: "r" read, "w" write (truncate), "a" append. "-" maps to
    stdin/stdout (reference: local_filesys stdin/stdout special-case).
    """
    from dmlc_tpu.io.filesys import FileSystem, URI  # cycle-free late import
    check(mode in ("r", "w", "a"), f"invalid stream mode {mode!r}")
    if uri == "-":
        import sys
        return FileStream(sys.stdin.buffer if mode == "r" else sys.stdout.buffer,
                          path="-")
    u = URI(uri)
    fs = FileSystem.get_instance(u, allow_null=allow_null)
    if fs is None:
        return None
    try:
        # resilience seam io.stream.open: transient open errors retry
        # under policy; FileNotFoundError is classified permanent and
        # propagates immediately (the allow_null contract below)
        return guarded("io.stream.open", lambda: fs.open(u, mode))
    except FileNotFoundError:
        if allow_null:
            return None
        raise


def create_seek_stream_for_read(uri: str,
                                allow_null: bool = False) -> Optional[SeekStream]:
    """Reference: SeekStream::CreateForRead."""
    from dmlc_tpu.io.filesys import FileSystem, URI
    u = URI(uri)
    fs = FileSystem.get_instance(u, allow_null=allow_null)
    if fs is None:
        return None
    try:
        return guarded("io.stream.open", lambda: fs.open_for_read(u))
    except FileNotFoundError:
        if allow_null:
            return None
        raise
