"""IO layer: streams, virtual filesystems, input splitting, RecordIO.

Reference: include/dmlc/{io,recordio,filesystem,input_split_shuffle}.h,
src/io.cc, src/io/*, src/recordio.cc.
"""

from dmlc_tpu.io.stream import (
    Stream, SeekStream, MemoryStream, Serializable, create_stream,
    create_seek_stream_for_read,
)
from dmlc_tpu.io.filesys import FileSystem, FileInfo, URI, LocalFileSystem
from dmlc_tpu.io.tempdir import TemporaryDirectory
from dmlc_tpu.io.input_split import InputSplit
from dmlc_tpu.io.recordio import (
    RecordIOWriter, RecordIOReader, RecordIOChunkReader, RECORDIO_MAGIC,
)
from dmlc_tpu.io.tpu_fs import (  # registers the tpu:// scheme on import
    TPUFileSystem, TPUSeekStream, recordio_device_batches,
)
from dmlc_tpu.io.pagestore import PageStore
from dmlc_tpu.io.streaming_split import StreamingSplit
from dmlc_tpu.io import objstore  # registers obj:// + s3:// on import

__all__ = [
    "Stream", "SeekStream", "MemoryStream", "Serializable", "create_stream",
    "create_seek_stream_for_read", "FileSystem", "FileInfo", "URI",
    "LocalFileSystem", "TemporaryDirectory", "InputSplit",
    "RecordIOWriter", "RecordIOReader", "RecordIOChunkReader", "RECORDIO_MAGIC",
    "TPUFileSystem", "TPUSeekStream", "recordio_device_batches",
    "PageStore", "StreamingSplit", "objstore",
]
