"""Chunk-level shuffled input split.

Reference: include/dmlc/input_split_shuffle.h — InputSplitShuffle::Create(
uri, part_index, num_parts, type, num_shuffle_parts, seed): the shard is
subdivided into ``num_shuffle_parts`` sub-shards whose read order is
permuted by a seeded RNG, reshuffled each epoch — coarse-grained shuffling
with deterministic replay (same seed + epoch ⇒ same order), which is the
property that makes data-side recovery trivial (SURVEY.md §5.3).
"""

from __future__ import annotations

from typing import Iterator, List, Optional

from dmlc_tpu.io.input_split import InputSplit
from dmlc_tpu.utils.logging import check

__all__ = ["InputSplitShuffle"]


class InputSplitShuffle(InputSplit):
    def __init__(self, uri: str, part_index: int, num_parts: int,
                 split_type: str = "text", num_shuffle_parts: int = 4,
                 seed: int = 0, **kwargs):
        check(num_shuffle_parts >= 1, "num_shuffle_parts must be >= 1")
        self._subs: List[InputSplit] = [
            InputSplit.create(uri, part_index * num_shuffle_parts + i,
                              num_parts * num_shuffle_parts, split_type,
                              **kwargs)
            for i in range(num_shuffle_parts)]
        self._seed = seed
        self._epoch = 0
        self.part_index, self.num_parts = part_index, num_parts
        self._num_shuffle_parts = num_shuffle_parts
        self._split_type = split_type
        self._uri = uri
        self._kwargs = kwargs
        self.before_first()

    @staticmethod
    def create(uri: str, part_index: int, num_parts: int,
               split_type: str = "text", num_shuffle_parts: int = 4,
               seed: int = 0, **kwargs) -> "InputSplitShuffle":
        """Reference: InputSplitShuffle::Create."""
        if num_shuffle_parts <= 1:
            return InputSplit.create(uri, part_index, num_parts, split_type,
                                     **kwargs)
        return InputSplitShuffle(uri, part_index, num_parts, split_type,
                                 num_shuffle_parts, seed, **kwargs)

    def before_first(self) -> None:
        from dmlc_tpu.shuffle.permutation import epoch_rng
        rng = epoch_rng(self._seed, self._epoch)
        self._order = rng.permutation(len(self._subs))
        self._epoch += 1
        self._cursor = 0
        for s in self._subs:
            s.before_first()

    def _current(self) -> Optional[InputSplit]:
        if self._cursor >= len(self._order):
            return None
        return self._subs[self._order[self._cursor]]

    def next_record(self) -> Optional[bytes]:
        while True:
            cur = self._current()
            if cur is None:
                return None
            rec = cur.next_record()
            if rec is not None:
                return rec
            self._cursor += 1

    def next_chunk(self) -> Optional[bytes]:
        while True:
            cur = self._current()
            if cur is None:
                return None
            chunk = cur.next_chunk()
            if chunk is not None:
                return chunk
            self._cursor += 1

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        return self._subs[0].extract_records(chunk)

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        self.__init__(self._uri, part_index, num_parts, self._split_type,
                      self._num_shuffle_parts, self._seed, **self._kwargs)

    def get_total_size(self) -> int:
        return self._subs[0].get_total_size()

    @property
    def bytes_read(self) -> int:
        return sum(s.bytes_read for s in self._subs)
