"""RecordIO framed binary record format.

Reference: include/dmlc/recordio.h + src/recordio.cc —
RecordIOWriter::WriteRecord (kMagic = 0xced7230a, EncodeLRec(cflag,len) =
cflag<<29 | len, cflag ∈ {0 whole, 1 start, 2 middle, 3 end}),
RecordIOReader::NextRecord, RecordIOChunkReader.

Format contract (frozen by round-trip property tests in
tests/test_io.py):

- A record is written as one or more *frames*. Each frame is
  ``magic(u32 LE) | lrec(u32 LE) | payload | pad-to-4B``, where
  ``lrec = cflag<<29 | payload_len`` (payload_len < 2^29).
- Magic-collision escaping: before writing, the payload is scanned at
  4-byte-aligned positions for the magic u32; each aligned occurrence is
  *removed* and becomes a frame boundary (the reader re-inserts the magic
  bytes when stitching frames back together). Hence the byte stream never
  contains the magic at a 4-byte-aligned position except at frame heads —
  which is what makes shard realignment by magic-scan sound
  (reference: src/io/recordio_split.cc SeekRecordBegin).
- cflag: 0 = whole record in one frame; multi-frame records use
  1 (start), 2 (middle), 3 (end).

Dense-record payload encoding (ABI 6, frozen — the native engine's
``recordio_dense`` decoder and the Python golden
``data/dense_record_parser.py`` both speak exactly this)::

    u32 n_values (LE) | f32 label (LE) | f32[n_values] values (LE)

A payload whose length is not exactly ``8 + 4 * n_values`` is corrupt
and must raise DMLCError (the engine raises EngineError) — never a
silently short row. ``DenseRecordWriter``/:func:`decode_dense_record`
are the round-trip pair the parity tests pin.

Image-record payload encoding (ABI 8, frozen — the native engine's
``recordio_image`` decoder and the Python golden
``data/image_record_parser.py`` both speak exactly this; the MXNet-
style ImageNet ``.rec`` scenario's raw/uniform-shape lane)::

    u32 h (LE) | u32 w (LE) | u32 c (LE) | f32 label (LE) |
    u8[h*w*c] pixels (HWC, row-major)

Same strict length contract: a payload whose byte length is not
exactly ``16 + h*w*c`` raises DMLCError/EngineError. Pixel bytes that
happen to spell the frame magic at a 4-aligned position escape into
multi-frame records exactly like any other payload — the framing layer
owns that, both decoders stitch it back.
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Optional, Tuple, Union

import numpy as np

from dmlc_tpu.io.stream import Stream
from dmlc_tpu.utils.logging import DMLCError, check

__all__ = [
    "RECORDIO_MAGIC", "RecordIOWriter", "RecordIOReader",
    "RecordIOChunkReader", "encode_lrec", "decode_flag", "decode_length",
    "DenseRecordWriter", "encode_dense_record", "decode_dense_record",
    "ImageRecordWriter", "encode_image_record", "decode_image_record",
]

RECORDIO_MAGIC = 0xced7230a
_MAGIC_BYTES = struct.pack("<I", RECORDIO_MAGIC)


def encode_lrec(cflag: int, length: int) -> int:
    """Reference: RecordIOWriter::EncodeLRec."""
    check(0 <= cflag < 4 and 0 <= length < (1 << 29),
          f"bad lrec cflag={cflag} len={length}")
    return (cflag << 29) | length


def decode_flag(rec: int) -> int:
    return (rec >> 29) & 7


def decode_length(rec: int) -> int:
    return rec & ((1 << 29) - 1)


class RecordIOWriter:
    """Reference: RecordIOWriter (src/recordio.cc)."""

    def __init__(self, stream: Stream):
        self._stream = stream
        self.escaped_magic_count = 0  # number of magic collisions escaped

    @property
    def except_counter(self) -> int:
        """Deprecated alias for ``escaped_magic_count`` — the reference
        RecordIOWriter's name (src/recordio.cc ``except_counter()``),
        kept so consumers following the README parity table keep
        working. See docs/CHANGES.md (round 3 rename)."""
        return self.escaped_magic_count

    def write_record(self, data: Union[bytes, bytearray, memoryview]) -> None:
        data = bytes(data)
        size = len(data)
        check(size < (1 << 29), "RecordIO: record too large (>= 2^29 bytes)")
        s = self._stream
        # scan 4-byte-aligned positions for the magic word; each aligned
        # occurrence is removed and becomes a frame boundary (the reader
        # re-inserts it when stitching) — only positions before the last
        # aligned word can hold a full aligned magic
        scan_end = (size >> 2) << 2
        frame_start = 0  # start of the not-yet-written remainder
        hit = data.find(_MAGIC_BYTES)
        while hit != -1 and hit < scan_end:
            if hit % 4 == 0:
                lrec = encode_lrec(1 if frame_start == 0 else 2,
                                   hit - frame_start)
                s.write(_MAGIC_BYTES)
                s.write(struct.pack("<I", lrec))
                if hit != frame_start:
                    s.write(data[frame_start:hit])
                frame_start = hit + 4
                self.escaped_magic_count += 1
                hit = data.find(_MAGIC_BYTES, frame_start)
            else:
                hit = data.find(_MAGIC_BYTES, hit + 1)
        lrec = encode_lrec(3 if frame_start != 0 else 0, size - frame_start)
        s.write(_MAGIC_BYTES)
        s.write(struct.pack("<I", lrec))
        if size != frame_start:
            s.write(data[frame_start:size])
        pad = (-size) % 4
        if pad:
            s.write(b"\x00" * pad)


class IndexedRecordIOWriter(RecordIOWriter):
    """RecordIO writer that also maintains a key→offset index.

    Reference: the ``key\\toffset`` index files consumed by
    src/io/indexed_recordio_split.cc (upstream generates them with
    MXNet-side tooling; here the writer produces them directly).
    The stream must be fresh (offsets count from its current position 0).
    """

    class _CountingStream:
        def __init__(self, inner: Stream):
            self.inner = inner
            self.written = 0

        def write(self, data) -> int:
            n = self.inner.write(data)
            self.written += len(data)
            return n

    def __init__(self, stream: Stream, index_stream: Stream):
        self._counter = self._CountingStream(stream)
        super().__init__(self._counter)
        self._index_stream = index_stream
        self._auto_key = 0

    def write_record(self, data, key: Optional[int] = None) -> None:
        if key is None:
            key = self._auto_key
            self._auto_key += 1
        self._index_stream.write(
            f"{key}\t{self._counter.written}\n".encode())
        super().write_record(data)


_DENSE_HDR = struct.Struct("<If")  # n_values, label


def encode_dense_record(label: float, values) -> bytes:
    """One dense record payload: ``u32 n | f32 label | f32[n] values``
    (all little-endian). ``values`` is any 1-D float sequence; the f32
    cast here IS the stored precision (decode returns the exact
    bits)."""
    vals = np.ascontiguousarray(values, dtype="<f4")
    check(vals.ndim == 1, "dense record: values must be 1-D")
    return _DENSE_HDR.pack(len(vals), float(label)) + vals.tobytes()


def decode_dense_record(payload) -> Tuple[np.float32, np.ndarray]:
    """Decode one dense payload to ``(label, values)``. The length
    contract is strict: a payload whose byte length disagrees with its
    recorded ``n_values`` raises DMLCError (byte parity with the
    engine's EngineError)."""
    n_bytes = len(payload)
    check(n_bytes >= _DENSE_HDR.size,
          f"dense record: payload shorter than its 8-byte header "
          f"({n_bytes} bytes)")
    n, label = _DENSE_HDR.unpack_from(payload)
    check(n_bytes == _DENSE_HDR.size + 4 * n,
          f"dense record: n_values {n} disagrees with payload length "
          f"{n_bytes}")
    values = np.frombuffer(payload, dtype="<f4", count=n,
                           offset=_DENSE_HDR.size)
    return np.float32(label), values


class DenseRecordWriter:
    """RecordIO writer of dense records — the Python golden for the
    engine's ABI-6 ``recordio_dense`` fast path. Magic-collision
    escaping comes free from :class:`RecordIOWriter` (a value whose f32
    bits equal the frame magic at a 4-aligned payload position becomes
    a multi-frame record; the decoders stitch it back)."""

    def __init__(self, stream: Stream):
        self._w = RecordIOWriter(stream)

    @property
    def escaped_magic_count(self) -> int:
        return self._w.escaped_magic_count

    def write(self, label: float, values) -> None:
        self._w.write_record(encode_dense_record(label, values))


_IMAGE_HDR = struct.Struct("<IIIf")  # h, w, c, label


def encode_image_record(label: float, pixels) -> bytes:
    """One image record payload: ``u32 h | u32 w | u32 c | f32 label |
    u8[h*w*c] pixels`` (HWC row-major, all little-endian). ``pixels``
    is any array-like coercible to a 3-D uint8 HWC array (a 2-D
    grayscale array gains a trailing channel axis of 1)."""
    px = np.ascontiguousarray(pixels, dtype=np.uint8)
    if px.ndim == 2:
        px = px[:, :, None]
    check(px.ndim == 3, "image record: pixels must be HWC (or HW)")
    h, w, c = px.shape
    return _IMAGE_HDR.pack(h, w, c, float(label)) + px.tobytes()


def decode_image_record(payload) -> Tuple[np.float32, np.ndarray]:
    """Decode one image payload to ``(label, pixels)`` — pixels an
    ``[h, w, c]`` uint8 view over the payload bytes. The length
    contract is strict: a payload whose byte length disagrees with its
    recorded shape raises DMLCError (byte parity with the engine's
    EngineError)."""
    n_bytes = len(payload)
    check(n_bytes >= _IMAGE_HDR.size,
          f"image record: payload shorter than its 16-byte header "
          f"({n_bytes} bytes)")
    h, w, c, label = _IMAGE_HDR.unpack_from(payload)
    npix = h * w * c
    check(n_bytes == _IMAGE_HDR.size + npix,
          f"image record: shape {h}x{w}x{c} disagrees with payload "
          f"length {n_bytes}")
    pixels = np.frombuffer(payload, dtype=np.uint8, count=npix,
                           offset=_IMAGE_HDR.size).reshape(h, w, c)
    return np.float32(label), pixels


class ImageRecordWriter:
    """RecordIO writer of raw HWC u8 image records — the Python golden
    for the engine's ABI-8 ``recordio_image`` decode lane (the MXNet-
    style ``.rec`` shape, raw/uniform pixels). Pixel runs that spell
    the frame magic at a 4-aligned payload position escape into
    multi-frame records via :class:`RecordIOWriter`, decoders stitch
    them back."""

    def __init__(self, stream: Stream):
        self._w = RecordIOWriter(stream)

    @property
    def escaped_magic_count(self) -> int:
        return self._w.escaped_magic_count

    def write(self, label: float, pixels) -> None:
        self._w.write_record(encode_image_record(label, pixels))


class RecordIOReader:
    """Reference: RecordIOReader (src/recordio.cc)."""

    def __init__(self, stream: Stream):
        self._stream = stream
        self._eos = False

    def next_record(self) -> Optional[bytes]:
        """Next record payload, or None at end of stream."""
        if self._eos:
            return None
        s = self._stream
        parts: List[bytes] = []
        while True:
            head = s.read(4)
            if len(head) == 0:
                self._eos = True
                check(not parts, "RecordIO: truncated multi-frame record")
                return None
            check(len(head) == 4, "RecordIO: truncated magic")
            check(struct.unpack("<I", head)[0] == RECORDIO_MAGIC,
                  "RecordIO: invalid magic number")
            lrec = struct.unpack("<I", s.read_exact(4))[0]
            cflag, clen = decode_flag(lrec), decode_length(lrec)
            payload = s.read_exact(clen)
            pad = (-clen) % 4
            if pad:
                s.read_exact(pad)
            if cflag == 0:
                check(not parts, "RecordIO: whole-frame inside multi-frame")
                return payload
            if cflag == 1:
                check(not parts, "RecordIO: start-frame inside multi-frame")
                parts.append(payload)
            elif cflag == 2:
                check(bool(parts), "RecordIO: middle-frame without start")
                parts.append(payload)
            else:  # end
                check(bool(parts), "RecordIO: end-frame without start")
                parts.append(payload)
                # re-insert the escaped magic between frames
                return _MAGIC_BYTES.join(parts)


class RecordIOChunkReader:
    """Extract records from an in-memory chunk of whole frames.

    Reference: RecordIOChunkReader(InputSplit::Blob) — used on the
    parse side where InputSplit hands us chunk buffers aligned to frame
    boundaries.
    """

    def __init__(self, chunk: Union[bytes, memoryview]):
        self._data = memoryview(chunk)
        self._pos = 0

    def next_record(self) -> Optional[bytes]:
        d, n = self._data, len(self._data)
        parts: List[bytes] = []
        while True:
            if self._pos >= n:
                check(not parts, "RecordIO chunk: truncated multi-frame record")
                return None
            check(self._pos + 8 <= n, "RecordIO chunk: truncated frame header")
            magic, lrec = struct.unpack_from("<II", d, self._pos)
            check(magic == RECORDIO_MAGIC, "RecordIO chunk: invalid magic")
            cflag, clen = decode_flag(lrec), decode_length(lrec)
            start = self._pos + 8
            check(start + clen <= n, "RecordIO chunk: truncated payload")
            payload = bytes(d[start:start + clen])
            self._pos = start + clen + ((-clen) % 4)
            if cflag == 0:
                check(not parts, "RecordIO chunk: whole-frame inside multi-frame")
                return payload
            if cflag == 1:
                check(not parts, "RecordIO chunk: start inside multi-frame")
                parts.append(payload)
            elif cflag == 2:
                check(bool(parts), "RecordIO chunk: middle without start")
                parts.append(payload)
            else:
                check(bool(parts), "RecordIO chunk: end without start")
                parts.append(payload)
                return _MAGIC_BYTES.join(parts)

    def __iter__(self) -> Iterator[bytes]:
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec
