"""User-URI decomposition: scheme + path + cache hint + kwargs.

Reference: src/io/uri_spec.h — io::URISpec{uri, cache_file, args}.

Convention (same as the reference / XGBoost data URIs):
``scheme://host/path?k1=v1&k2=v2#cachefile`` — '#' introduces a local
cache-file hint (reference: CachedInputSplit), '?' introduces parser
kwargs such as ``format=csv``. ';' in the path separates multiple input
paths, each keeping its own scheme.

Scheme handling: the ``scheme://`` prefix is split off BEFORE the
'?'/'#' decomposition, so a remote URI like
``obj://bucket/key?format=csv#cache`` round-trips with its protocol
intact (``str_spec()`` reconstructs the raw form) — the '?'/'#'
splitting predates any scheme support and must never eat into one.
"""

from __future__ import annotations

from typing import Dict, List

__all__ = ["URISpec"]


class URISpec:
    __slots__ = ("uri", "cache_file", "args")

    def __init__(self, raw: str):
        # split the scheme off first: '?'/'#' decomposition applies to
        # the scheme-less remainder only (a pathological '?'/'#' inside
        # a scheme name must not shift the parse)
        scheme = ""
        rest = raw
        if "://" in raw:
            proto, _, tail = raw.partition("://")
            if "?" not in proto and "#" not in proto:
                scheme = proto + "://"
                rest = tail
        path, hash_, cache = rest.partition("#")
        self.cache_file: str = cache if hash_ else ""
        path, q, argstr = path.partition("?")
        self.uri: str = scheme + path
        self.args: Dict[str, str] = {}
        if q:
            for kv in argstr.split("&"):
                if not kv:
                    continue
                k, _, v = kv.partition("=")
                self.args[k] = v

    @property
    def scheme(self) -> str:
        """Protocol of the (first) path, "file://" when bare."""
        first = self.uri.split(";", 1)[0]
        if "://" in first:
            return first.partition("://")[0] + "://"
        return "file://"

    def paths(self) -> List[str]:
        """';'-separated multi-path expansion; every path keeps the
        scheme it was written with."""
        return [p for p in self.uri.split(";") if p]

    def str_spec(self) -> str:
        """Reconstruct the raw user URI (protocol, ?args and #cache
        intact) — the round-trip contract tests pin."""
        out = self.uri
        if self.args:
            out += "?" + "&".join(f"{k}={v}" for k, v in self.args.items())
        if self.cache_file:
            out += "#" + self.cache_file
        return out

    def __repr__(self) -> str:
        return (f"URISpec(uri={self.uri!r}, cache_file={self.cache_file!r}, "
                f"args={self.args!r})")
