"""User-URI decomposition: path + cache hint + kwargs.

Reference: src/io/uri_spec.h — io::URISpec{uri, cache_file, args}.

Convention (same as the reference / XGBoost data URIs):
``path?k1=v1&k2=v2#cachefile`` — '#' introduces a local cache-file hint
(reference: CachedInputSplit), '?' introduces parser kwargs such as
``format=csv``. ';' in the path separates multiple input paths.
"""

from __future__ import annotations

from typing import Dict, List

__all__ = ["URISpec"]


class URISpec:
    __slots__ = ("uri", "cache_file", "args")

    def __init__(self, raw: str):
        path, hash_, cache = raw.partition("#")
        self.cache_file: str = cache if hash_ else ""
        path, q, argstr = path.partition("?")
        self.uri: str = path
        self.args: Dict[str, str] = {}
        if q:
            for kv in argstr.split("&"):
                if not kv:
                    continue
                k, _, v = kv.partition("=")
                self.args[k] = v

    def paths(self) -> List[str]:
        """';'-separated multi-path expansion."""
        return [p for p in self.uri.split(";") if p]

    def __repr__(self) -> str:
        return (f"URISpec(uri={self.uri!r}, cache_file={self.cache_file!r}, "
                f"args={self.args!r})")
