"""Indexed RecordIO split: random access by index file, shuffled batch reads.

Reference: src/io/indexed_recordio_split.{h,cc} — IndexedRecordIOSplitter;
index file is text lines ``key\\toffset`` (offsets ascending, byte offset of
each record's first frame in the data file).

Partitioning: each index entry (a record) belongs to the part whose raw
byte range [nstep*k, nstep*(k+1)) contains its offset — same contract as
the byte-range splits, exact at record granularity. With ``shuffle=True``
records are read in batches of ``batch_size`` whose order is permuted by a
seeded RNG, reshuffled every epoch (reference: shuffled batched reads with
derandomizable seed).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import numpy as np

from dmlc_tpu.io.filesys import FileSystem, URI
from dmlc_tpu.io.input_split import InputSplit
from dmlc_tpu.io.recordio import RecordIOReader
from dmlc_tpu.io.stream import create_seek_stream_for_read, create_stream
from dmlc_tpu.io.uri_spec import URISpec
from dmlc_tpu.utils.logging import DMLCError, check, check_lt

__all__ = ["IndexedRecordIOSplit"]


class IndexedRecordIOSplit(InputSplit):
    def __init__(self, uri: str, part_index: int, num_parts: int, *,
                 index_uri: Optional[str] = None, shuffle: bool = False,
                 seed: int = 0, batch_size: int = 256):
        spec = URISpec(uri)
        paths = spec.paths()
        check(len(paths) == 1,
              "indexed_recordio expects a single data file")
        self._data_uri = paths[0]
        self._index_uri = index_uri or spec.args.get("index") or (
            self._data_uri + ".idx")
        u = URI(self._data_uri)
        self._file_size = FileSystem.get_instance(u).get_path_info(u).size
        self._entries = self._read_index(self._index_uri, self._file_size)
        self._total = self._file_size
        self._shuffle = shuffle
        self._seed = seed
        self._batch_size = max(1, batch_size)
        self._epoch = 0
        self._bytes_read = 0
        self.reset_partition(part_index, num_parts)

    @staticmethod
    def _read_index(index_uri: str, file_size: int) -> List[Tuple[int, int, int]]:
        """[(key, offset, size)] with sizes from consecutive offsets."""
        with create_stream(index_uri, "r") as s:
            text = s.read_all().decode("utf-8")
        raw: List[Tuple[int, int]] = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            parts = line.split()
            check(len(parts) >= 2, f"bad index line {line!r}")
            raw.append((int(parts[0]), int(parts[1])))
        raw.sort(key=lambda kv: kv[1])
        out = []
        for i, (key, off) in enumerate(raw):
            end = raw[i + 1][1] if i + 1 < len(raw) else file_size
            check(end >= off, "index offsets not ascending")
            out.append((key, off, end - off))
        return out

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        check_lt(part_index, num_parts)
        nstep = (self._total + num_parts - 1) // num_parts
        lo, hi = nstep * part_index, nstep * (part_index + 1)
        self._mine = [e for e in self._entries if lo <= e[1] < hi]
        self.part_index, self.num_parts = part_index, num_parts
        self.before_first()

    def before_first(self) -> None:
        order = np.arange(len(self._mine))
        if self._shuffle:
            from dmlc_tpu.shuffle.permutation import epoch_rng
            nbatch = (len(order) + self._batch_size - 1) // self._batch_size
            rng = epoch_rng(self._seed, self._epoch)
            batches = [order[b * self._batch_size:(b + 1) * self._batch_size]
                       for b in rng.permutation(nbatch)]
            order = np.concatenate(batches) if batches else order
            self._epoch += 1
        self._order = order
        self._pos = 0
        self._stream = None

    def keys(self) -> List[int]:
        """Index keys of this part's records, in current read order."""
        return [self._mine[i][0] for i in self._order]

    def record_windows(self) -> Tuple[np.ndarray, np.ndarray]:
        """(offsets, sizes) int64 arrays of this part's record windows in
        table order — the data-plane contract for block readers (the
        native engine maps the file and reads windows by id)."""
        offs = np.array([e[1] for e in self._mine], np.int64)
        sizes = np.array([e[2] for e in self._mine], np.int64)
        return offs, sizes

    def next_order_batch(self) -> Optional[np.ndarray]:
        """Record ids (into the part's window table) of the next batch in
        the current epoch order; advances the cursor. None when the epoch
        is exhausted. Shares the cursor with next_record/next_chunk."""
        if self._pos >= len(self._order):
            return None
        b = self._order[self._pos:self._pos + self._batch_size]
        self._pos += len(b)
        return np.ascontiguousarray(b, np.int64)

    def next_record(self) -> Optional[bytes]:
        if self._pos >= len(self._order):
            return None
        _, off, size = self._mine[self._order[self._pos]]
        self._pos += 1
        if self._stream is None:
            self._stream = create_seek_stream_for_read(self._data_uri)
        self._stream.seek(off)
        payload = self._stream.read_exact(size)
        self._bytes_read += size
        rec = RecordIOReader(_BytesStream(payload)).next_record()
        check(rec is not None, "indexed_recordio: empty record at offset")
        return rec

    def next_chunk(self) -> Optional[bytes]:
        """One batch of framed records as a raw chunk."""
        if self._pos >= len(self._order):
            return None
        out = []
        for _ in range(self._batch_size):
            if self._pos >= len(self._order):
                break
            _, off, size = self._mine[self._order[self._pos]]
            self._pos += 1
            if self._stream is None:
                self._stream = create_seek_stream_for_read(self._data_uri)
            self._stream.seek(off)
            out.append(self._stream.read_exact(size))
            self._bytes_read += size
        return b"".join(out)

    def extract_records(self, chunk: bytes) -> Iterator[bytes]:
        from dmlc_tpu.io.recordio import RecordIOChunkReader
        return iter(RecordIOChunkReader(chunk))

    def get_total_size(self) -> int:
        return self._total

    @property
    def bytes_read(self) -> int:
        return self._bytes_read


class _BytesStream:
    """Minimal read-only Stream over bytes for RecordIOReader."""

    def __init__(self, data: bytes):
        self._d = data
        self._p = 0

    def read(self, n: int) -> bytes:
        b = self._d[self._p:self._p + n]
        self._p += len(b)
        return b

    def read_exact(self, n: int) -> bytes:
        b = self.read(n)
        if len(b) != n:
            raise DMLCError("unexpected EOF in record window")
        return b
