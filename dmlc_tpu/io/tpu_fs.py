"""tpu:// URI scheme: streams that stage bytes straight into device HBM.

The north-star contract (BASELINE.json): "Stream/SeekStream gain a
tpu:// URI that DMAs RecordIO chunks straight to device". There is no
portable file->HBM DMA primitive in JAX, so the honest TPU-native
mechanism is: host staging read + ASYNC ``jax.device_put`` (which on TPU
runtimes is a DMA from host staging memory over PCIe/ICI), with a
lookahead window so transfer N+1 is in flight while the consumer uses
chunk N. That is exactly the reference's ThreadedInputSplit double-buffer
re-aimed at the host->HBM edge.

URI shape: ``tpu:///abs/path`` (or ``tpu://rel/path``) — the path after
the scheme is served by the local VFS. Reads/seeks behave as a normal
SeekStream (host bytes); the device-side API is additive:

- ``TPUSeekStream.read_to_device(n)`` -> device-resident uint8 jax.Array
- ``TPUSeekStream.device_chunks(chunk_bytes, lookahead)`` -> iterator of
  device chunks with ``lookahead`` transfers in flight
- ``recordio_device_batches(uri, part, nparts)`` -> sharded RecordIO
  record batches as device arrays (payload u8 + starts/ends i64), the
  "RecordIO chunks straight to device" path, zero host-side record copy
  when the native engine is built.

Writes accept bytes or (jax/numpy) arrays — a device array is pulled to
host once and streamed out, which is the checkpoint-write direction.

Reference seam: src/io/filesys.cc scheme registry + the io.h Stream
contract; no reference counterpart exists for the device staging (CUDA
GPUDirect would be the CUDA-world analogue; XLA exposes no equivalent,
so device_put IS the TPU-native transport).
"""

from __future__ import annotations

from typing import Iterator, List, Optional

from dmlc_tpu.io.filesys import FileInfo, FileSystem, URI
from dmlc_tpu.io.stream import SeekStream, Stream
from dmlc_tpu.utils.logging import check

__all__ = ["TPUFileSystem", "TPUSeekStream", "TPUWriteStream",
           "recordio_device_batches"]

_SCHEME = "tpu://"


def _inner_path(uri: URI) -> str:
    """tpu:///abs/x -> /abs/x ; tpu://rel/x -> rel/x."""
    return uri.host + uri.name


def local_path(uri: str) -> str:
    """Map a (possibly tpu://) URI to its backing local path — the one
    scheme-strip rule, shared by the native bindings and the device
    ingest helpers."""
    if uri.startswith(_SCHEME):
        return _inner_path(URI(uri))
    return uri


def _device_put_safe(v, device, plat: str, recycled: bool):
    """device_put with the CPU-aliasing rule in ONE place: on the CPU
    backend jax.device_put may ALIAS host memory instead of copying, so
    any source that gets recycled/overwritten later (pooled staging
    buffers, leased native arenas) must be copied first. Real
    accelerator transfers always copy."""
    import jax
    import numpy as np
    if recycled and plat == "cpu":
        v = np.array(v, copy=True)
    return jax.device_put(v, device) if device is not None else \
        jax.device_put(v)


def _platform(device) -> str:
    import jax
    return device.platform if device is not None else jax.default_backend()


class TPUSeekStream(SeekStream):
    """SeekStream over host bytes + device-chunk staging API."""

    def __init__(self, inner: SeekStream, path: str):
        self._inner = inner
        self.path = path

    # -- plain SeekStream (host bytes)

    def read(self, nbytes: int) -> bytes:
        return self._inner.read(nbytes)

    def write(self, data) -> int:  # pragma: no cover - read stream
        return self._inner.write(data)

    def seek(self, pos: int) -> None:
        self._inner.seek(pos)

    def tell(self) -> int:
        return self._inner.tell()

    def close(self) -> None:
        self._inner.close()

    # -- device staging

    def read_to_device(self, nbytes: int, device=None):
        """Read up to nbytes from the current position into device HBM.

        Returns a uint8 jax.Array (async transfer — not blocked on), or
        None at EOF. The transfer is enqueued immediately; callers that
        need completion use jax.block_until_ready.

        Unlike ``device_chunks`` this path does NOT stage through the
        BufferPool: the staging buffer's lifetime escapes the call (the
        async transfer may still be reading it when we return), and this
        one-shot API has no later point at which to observe completion
        and recycle — ``device_chunks`` can pool only because its loop
        sees each transfer land before releasing the buffer.
        """
        import jax
        import numpy as np
        raw = self._inner.read(nbytes)
        if not raw:
            return None
        host = np.frombuffer(raw, dtype=np.uint8)
        return (jax.device_put(host, device) if device is not None
                else jax.device_put(host))

    def device_chunks(self, chunk_bytes: int = 4 << 20, lookahead: int = 2,
                      device=None, pool=None) -> Iterator:
        """Iterate the stream as device-resident uint8 chunks with
        ``lookahead`` transfers in flight (read/transfer overlap).

        Transfers stage through a ring of REUSED host buffers
        (utils.memory.BufferPool; default the thread-local pool): each
        chunk reads in place into a warm buffer (Stream.readinto) and
        the buffer is recycled once its transfer has landed, instead of
        allocating + first-touch-faulting a fresh bytes object per
        chunk. On the CPU backend jax.device_put may alias the host
        buffer, so the staged view is copied there (pooling pays only on
        real accelerator transfers, which always copy).

        The 4 MB default chunk matches the measured transfer sweet spot
        on the v5e tunnel (r3: pooled 1.28 GB/s median vs 1.14 unpooled
        at 4 MB over 5 interleaved runs). r4 re-measured the ceiling:
        fresh-state single stream does 1.5-1.7 GB/s at 1-4 MB chunks,
        8 MB+ is never better, and the dramatic collapses are the
        tunnel's burst shaping, not chunk size — see BASELINE.md
        "Transfer ceiling" and dmlc_tpu.bench_transfer."""
        import jax
        from dmlc_tpu.utils.memory import thread_local_pool
        check(lookahead >= 1, "lookahead must be >= 1")
        if pool is None:
            pool = thread_local_pool()
        plat = _platform(device)
        pending: List = []  # (device chunk, staging buffer to recycle)
        eof = False
        try:
            while True:
                while not eof and len(pending) < lookahead:
                    buf = pool.acquire(chunk_bytes)
                    got = self._inner.readinto(
                        memoryview(buf)[:chunk_bytes])
                    if not got:
                        pool.release(buf)
                        eof = True
                        break
                    dev = _device_put_safe(buf[:got], device, plat,
                                           recycled=True)
                    pending.append((dev, buf))
                if not pending:
                    return
                dev, buf = pending.pop(0)
                jax.block_until_ready(dev)  # transfer done: buf reusable
                pool.release(buf)
                yield dev
        finally:
            # consumer abandoned the generator (break/close/GC) with
            # transfers still in flight: drain them before releasing the
            # staging buffers, or the pool could hand a buffer that an
            # async device_put is still reading to the next reader
            # (ADVICE r3)
            for dev, buf in pending:
                jax.block_until_ready(dev)
                pool.release(buf)


class TPUWriteStream(Stream):
    """Write stream accepting bytes or arrays (device arrays are pulled
    to host once — the checkpoint-write direction)."""

    def __init__(self, inner: Stream, path: str):
        self._inner = inner
        self.path = path

    def write(self, data) -> int:
        if not isinstance(data, (bytes, bytearray, memoryview)):
            import numpy as np
            data = np.asarray(data).tobytes()  # device -> host, once
        return self._inner.write(data)

    def read(self, nbytes: int) -> bytes:  # pragma: no cover - write stream
        return self._inner.read(nbytes)

    def close(self) -> None:
        self._inner.close()


class TPUFileSystem(FileSystem):
    """tpu:// scheme: local VFS metadata + device-staging streams."""

    def _local(self) -> FileSystem:
        fs = FileSystem.get_instance(URI("/"))
        assert fs is not None
        return fs

    def open(self, uri: URI, mode: str) -> Stream:
        path = _inner_path(uri)
        inner = self._local().open(URI(path), mode)
        if mode == "r":
            return TPUSeekStream(inner, path)
        return TPUWriteStream(inner, path)

    def open_for_read(self, uri: URI) -> TPUSeekStream:
        path = _inner_path(uri)
        return TPUSeekStream(self._local().open_for_read(URI(path)), path)

    def get_path_info(self, uri: URI) -> FileInfo:
        info = self._local().get_path_info(URI(_inner_path(uri)))
        return FileInfo(path=_SCHEME + info.path, size=info.size,
                        type=info.type, mtime_ns=info.mtime_ns)

    def list_directory(self, uri: URI) -> List[FileInfo]:
        return [FileInfo(path=_SCHEME + fi.path, size=fi.size,
                         type=fi.type, mtime_ns=fi.mtime_ns)
                for fi in self._local().list_directory(URI(_inner_path(uri)))]


def recordio_device_batches(uri: str, part_index: int = 0,
                            num_parts: int = 1, *,
                            chunk_size: int = 4 << 20, lookahead: int = 2,
                            device=None) -> Iterator[dict]:
    """Sharded RecordIO ingest straight to device HBM.

    Yields dicts {"payload": u8 jax.Array, "starts": i64, "ends": i64}
    (record i = payload[starts[i]:ends[i]]). With the native engine the
    host path is zero-copy (engine chunk buffer -> device_put) and
    ``lookahead`` batches' transfers overlap the next chunk's read+decode;
    falls back to the Python split otherwise. Accepts plain or tpu://
    URIs (the scheme prefix is stripped for the byte source).
    """
    import jax
    import numpy as np
    uri = local_path(uri)
    check(lookahead >= 1, "lookahead must be >= 1")

    plat = _platform(device)

    def _put(arrs, leased: bool):
        # leased native arenas get recycled on release → the shared
        # CPU-aliasing rule in _device_put_safe applies (the python
        # fallback's buffers are owned, leased=False)
        return {k: _device_put_safe(v, device, plat, recycled=leased)
                for k, v in arrs.items()}

    from dmlc_tpu.native import native_available
    pending: List = []  # (device batch, lease or None)
    if native_available():
        from dmlc_tpu.native.bindings import NativeRecordIOReader
        reader = NativeRecordIOReader(uri, part_index, num_parts,
                                      chunk_size=chunk_size)
        try:
            while True:
                batch = reader.next_batch()
                if batch is None:
                    break
                data, starts, ends = batch
                dev = _put({"payload": data, "starts": starts,
                            "ends": ends}, leased=True)
                pending.append((dev, reader.detach()))
                if len(pending) > lookahead:
                    out, lease = pending.pop(0)
                    jax.block_until_ready(out)
                    if lease is not None:
                        lease.release()
                    yield out
            while pending:
                out, lease = pending.pop(0)
                jax.block_until_ready(out)
                if lease is not None:
                    lease.release()
                yield out
        finally:
            # early close/exception: in-flight transfers still read the
            # leased native buffers — drain before destroy frees them
            for out, lease in pending:
                jax.block_until_ready(out)
                if lease is not None:
                    lease.release()
            reader.destroy()
        return
    # python fallback: one batch per split chunk
    from dmlc_tpu.io.input_split import InputSplit
    split = InputSplit.create(uri, part_index, num_parts, "recordio",
                              chunk_size=chunk_size)
    while True:
        chunk = split.next_chunk()
        if chunk is None:
            break
        records = list(split.extract_records(chunk))
        if not records:
            continue
        payload = np.frombuffer(b"".join(records), dtype=np.uint8)
        ends = np.cumsum([len(r) for r in records], dtype=np.int64)
        starts = np.concatenate([[0], ends[:-1]]).astype(np.int64)
        dev = _put({"payload": payload, "starts": starts, "ends": ends},
                   leased=False)
        pending.append((dev, None))
        if len(pending) > lookahead:
            out, _ = pending.pop(0)
            yield out
    for out, _ in pending:
        yield out


FileSystem.register_scheme(_SCHEME, TPUFileSystem)
