"""Elastic gang supervision: restart a dead worker, don't kill the gang.

Reference: the production dmlc-core tracker keeps a job alive through
worker deaths via its ``recover`` handshake — a replacement worker
rejoins with the same rank and ``DMLC_NUM_ATTEMPT`` bumped (SURVEY
§5.3). This repo's determinism contract (a shard stream is a pure
function of (uri, part, num_parts, seed, epoch) — proven by
tests/test_elastic.py) makes the data-plane half of that trivial: a
restarted worker with the SAME coordinates replays the byte-identical
stream. This module performs the restart.

:class:`GangSupervisor` owns the process gang ``launch_local`` spawns:

- polls every member, distinguishing **exited 0 early** (a finished
  worker — the gang keeps running) from **died** (nonzero exit or
  signal);
- with a :class:`RestartPolicy`, a dead WORKER is respawned with its
  same env/coordinates and ``DMLC_TPU_ATTEMPT`` (alias
  ``DMLC_NUM_ATTEMPT``) bumped, after an exponential backoff — up to a
  per-worker and gang-wide budget. Each restart increments the
  ``resilience.restart`` counter (``dmlc_resilience_restart_total`` on
  /metrics), sets the ``resilience.gang.restarts`` gauge, warns
  through obs.log, and lands as a ``gang/restart/<member>`` instant on
  the supervisor's trace track (merged into ``trace-gang.json``);
- budget exhausted (or a non-worker death, or no policy): the whole
  gang is killed promptly — never a hang — and, when restart
  supervision was active and a flight dir is known, a launcher-side
  flight bundle (reason ``gang_restart_budget_exhausted``) records the
  teardown;
- PS service roles (scheduler/servers) that outlive every worker by
  more than a grace window are terminated cleanly and report exit 0:
  service processes wait for work forever by design, and "all workers
  finished" IS their clean shutdown signal (the grace lets roles that
  exit on their own do so untouched).

jax.distributed caveat: a restarted process cannot rejoin a LIVE
jax.distributed rendezvous (the coordinator holds the dead process's
slot) — restart supervision is for data-plane gangs built on the
determinism contract (no cross-worker barriers), or for whole-job
retry wrappers. docs/resilience.md spells out the boundary.
"""

from __future__ import annotations

import os
import subprocess
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

from dmlc_tpu.utils.logging import DMLCError, check

__all__ = ["RestartPolicy", "GangMember", "GangSupervisor",
           "ENV_ATTEMPT", "ENV_ATTEMPT_ALIAS"]

# restart-attempt env contract (reference: DMLC_NUM_ATTEMPT, set as an
# alias too): 0 on first spawn, +1 per supervisor restart. Fault-plan
# clauses scope on it (attempt=0 = "only before the first restart").
ENV_ATTEMPT = "DMLC_TPU_ATTEMPT"
# the reference tracker's own name for the same counter (SURVEY §2.3):
# spawn() stamps BOTH on every (re)spawn so reference-style workers
# and the rendezvous join contract read the attempt without knowing
# this repo's prefix
ENV_ATTEMPT_ALIAS = "DMLC_NUM_ATTEMPT"


@dataclass
class RestartPolicy:
    """How a dead worker is brought back.

    ``max_restarts`` is per worker; ``max_total_restarts`` bounds the
    gang (default: ``max_restarts * num_workers``). Backoff between a
    death and its respawn is exponential in the member's restart
    count — a crash-looping worker must not busy-spin the host."""

    max_restarts: int = 2
    max_total_restarts: Optional[int] = None
    backoff_base_s: float = 0.1
    backoff_multiplier: float = 2.0
    backoff_max_s: float = 5.0

    def backoff_for(self, restarts: int) -> float:
        return min(self.backoff_max_s,
                   self.backoff_base_s
                   * self.backoff_multiplier ** max(0, restarts - 1))


class GangMember:
    """One supervised process slot: role, coordinates, env — and the
    attempt counter that survives respawns."""

    def __init__(self, name: str, role: str, task_id: int,
                 command: Sequence[str], env: Dict[str, str]):
        self.name = name
        self.role = role
        self.task_id = task_id
        self.command = list(command)
        self.env = dict(env)
        self.proc: Optional[subprocess.Popen] = None
        self.attempt = 0
        self.restarts = 0
        self.code: Optional[int] = None
        self.restart_due: Optional[float] = None

    def spawn(self) -> None:
        env = dict(self.env)
        env[ENV_ATTEMPT] = str(self.attempt)
        env[ENV_ATTEMPT_ALIAS] = str(self.attempt)
        self.proc = subprocess.Popen(self.command, env=env)

    def running(self) -> bool:
        return (self.code is None and self.proc is not None
                and self.proc.poll() is None)


class GangSupervisor:
    """Poll-loop owner of a launch_local gang (see module docstring)."""

    def __init__(self, members: List[GangMember],
                 restart_policy: Optional[RestartPolicy] = None,
                 timeout: Optional[float] = None,
                 poll_interval_s: float = 0.05,
                 trace_dir: Optional[str] = None,
                 flight_dir: Optional[str] = None,
                 ps_grace_s: float = 10.0,
                 rendezvous_addr: Optional[tuple] = None,
                 rendezvous_gang: str = "local",
                 elastic: bool = False):
        check(len(members) >= 1, "GangSupervisor needs members")
        self.members = members
        self.restart_policy = restart_policy
        self.timeout = timeout
        self.poll_interval_s = poll_interval_s
        self.trace_dir = trace_dir
        self.flight_dir = flight_dir
        # rendezvous wiring (launch_local(rendezvous=True)): deaths
        # are REPORTED to the service — the membership epoch bumps
        # immediately instead of waiting out the heartbeat grace —
        # and with ``elastic`` a worker whose restart budget is gone
        # LEAVES the gang (survivors reshard over the new world)
        # rather than killing it
        self.rendezvous_addr = rendezvous_addr
        self.rendezvous_gang = rendezvous_gang
        self.elastic = bool(elastic)
        # how long PS service roles may linger after the last worker
        # finishes before the supervisor terminates them: roles that
        # exit on their own (role-generic test binaries) get to, while
        # a real scheduler blocked waiting for work forever cannot
        # hang the launch (the pre-resilience poll loop did)
        self.ps_grace_s = ps_grace_s
        self.total_restarts = 0
        self._rec = None
        if trace_dir is not None:
            from dmlc_tpu.obs.trace import TraceRecorder
            self._rec = TraceRecorder(8192)

    # -- events / telemetry

    def _event(self, kind: str, m: GangMember,
               args: Optional[Dict[str, Any]] = None) -> None:
        payload = {"role": m.role, "task_id": m.task_id,
                   "attempt": m.attempt, **(args or {})}
        name = f"gang/{kind}/{m.name}"
        try:
            from dmlc_tpu.obs import trace
            trace.instant(name, "resilience", payload)
            if self._rec is not None:
                self._rec.instant(name, "resilience", payload)
        except Exception:  # noqa: BLE001 — telemetry must not kill the gang
            pass

    def _report_death(self, m: GangMember) -> None:
        """Tell the rendezvous service a member died — supervision is
        the FAST death signal (the heartbeat grace is the slow one):
        the epoch bumps now, survivors learn the shrunken roster at
        their next beat. Best-effort: a missing or already-closed
        service must never take the supervisor down."""
        if self.rendezvous_addr is None:
            return
        try:
            from dmlc_tpu.rendezvous import service as _rndv
            _rndv.call(self.rendezvous_addr[0],
                       self.rendezvous_addr[1],
                       {"op": "report_death",
                        "gang": self.rendezvous_gang,
                        "member": m.name}, timeout_s=1.0)
        except Exception:  # noqa: BLE001 — best-effort report
            pass

    def _note_restart(self, m: GangMember, rc: int, delay: float) -> None:
        self.total_restarts += 1
        try:
            from dmlc_tpu.obs.metrics import REGISTRY
            REGISTRY.counter("resilience.restart").inc()
            REGISTRY.gauge("resilience.gang.restarts").set(
                self.total_restarts)
            from dmlc_tpu.obs.log import warn_limited
            warn_limited(
                f"gang-restart-{m.name}",
                f"resilience: {m.name} died (exit {rc}); restarting with "
                f"same coordinates in {delay:.2f}s (attempt "
                f"{m.attempt} -> {m.attempt + 1}, restart {m.restarts}"
                f"/{self.restart_policy.max_restarts})",
                min_interval_s=1.0, all_ranks=True)
        except Exception:  # noqa: BLE001
            pass
        self._event("restart", m, {"exit_code": rc,
                                   "delay_s": round(delay, 3),
                                   "restart": m.restarts})

    def _export_trace(self) -> None:
        if self._rec is None or self.trace_dir is None:
            return
        try:
            from dmlc_tpu.obs.export import write_chrome
            write_chrome(self._rec,
                         os.path.join(self.trace_dir,
                                      "trace-supervisor.json"),
                         process_name="dmlc_tpu gang supervisor")
        except Exception:  # noqa: BLE001 — best-effort export
            pass

    def _flight_bundle(self, reason: str,
                       detail: Dict[str, Any]) -> None:
        """Launcher-side post-mortem on graceful-degrade teardown."""
        try:
            from dmlc_tpu.obs import flight
            fl = flight.active()
            if fl is None:
                if self.flight_dir is None:
                    return
                fl = flight.FlightRecorder(out_dir=self.flight_dir)
            fl.dump(reason, stall_report=detail)
        except Exception:  # noqa: BLE001 — the raise below still happens
            pass

    # -- teardown

    def _kill_all(self) -> None:
        for m in self.members:
            if m.proc is not None and m.proc.poll() is None:
                m.proc.kill()
        for m in self.members:
            if m.proc is not None:
                m.proc.wait()

    def _codes(self) -> List[Optional[int]]:
        return [m.code if m.code is not None
                else (m.proc.returncode if m.proc is not None else None)
                for m in self.members]

    def _fail(self, m: GangMember, rc: int, budget_exhausted: bool) -> None:
        self._event("exit", m, {"code": rc, "fatal": True})
        self._kill_all()
        codes = self._codes()
        if budget_exhausted:
            self._flight_bundle(
                "gang_restart_budget_exhausted",
                {"member": m.name, "exit_code": rc,
                 "restarts": {x.name: x.restarts for x in self.members},
                 "total_restarts": self.total_restarts,
                 "exit_codes": codes})
            raise DMLCError(
                f"worker failure, exit codes {codes} (restart budget "
                f"exhausted after {self.total_restarts} restart(s); "
                "gang killed)")
        raise DMLCError(
            f"worker failure, exit codes {codes} (gang killed "
            "on first nonzero exit)")

    def _drain_ps_roles(self) -> None:
        """All workers finished cleanly and the grace window passed;
        scheduler/server processes wait for work forever by design —
        terminate them and report 0 (the pre-resilience poll loop hung
        on them instead)."""
        lingering = [m for m in self.members
                     if m.role != "worker" and m.code is None]
        for m in lingering:
            if m.proc is not None and m.proc.poll() is None:
                m.proc.terminate()
        deadline = time.monotonic() + 5.0
        for m in lingering:
            if m.proc is None:
                m.code = 0
                continue
            while m.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.02)
            if m.proc.poll() is None:
                m.proc.kill()
                m.proc.wait()
            m.code = 0
            self._event("ps.terminate", m)

    # -- restart decision

    def _may_restart(self, m: GangMember) -> bool:
        pol = self.restart_policy
        if pol is None or m.role != "worker":
            return False
        if m.restarts >= pol.max_restarts:
            return False
        total_cap = (pol.max_total_restarts
                     if pol.max_total_restarts is not None
                     else pol.max_restarts
                     * sum(1 for x in self.members
                           if x.role == "worker"))
        return self.total_restarts < total_cap

    # -- the loop

    def run(self) -> List[int]:
        deadline = (time.monotonic() + self.timeout
                    if self.timeout else None)
        workers_done_at: Optional[float] = None
        try:
            for m in self.members:
                m.spawn()
                self._event("spawn", m)
            while True:
                now = time.monotonic()
                if deadline is not None and now > deadline:
                    self._kill_all()
                    raise DMLCError(
                        f"workers exceeded timeout {self.timeout}s; "
                        "all killed")
                for m in self.members:
                    if m.code is not None:
                        continue
                    if m.restart_due is not None:
                        if now >= m.restart_due:
                            m.restart_due = None
                            m.attempt += 1
                            m.spawn()
                            self._event("spawn", m,
                                        {"after_restart": True})
                        continue
                    if m.proc is None:
                        continue
                    rc = m.proc.poll()
                    if rc is None:
                        continue
                    if rc == 0:
                        # exited 0 early: a FINISHED member, not a dead
                        # one — the rest of the gang keeps running
                        m.code = 0
                        self._event("exit", m, {"code": 0})
                        continue
                    self._report_death(m)
                    if self._may_restart(m):
                        m.restarts += 1
                        delay = self.restart_policy.backoff_for(
                            m.restarts)
                        m.restart_due = now + delay
                        self._note_restart(m, rc, delay)
                        continue
                    if (self.elastic and m.role == "worker"
                            and any(x is not m and x.code is None
                                    for x in self.members
                                    if x.role == "worker")):
                        # elastic mode: a permanently dead worker is
                        # a membership SHRINK, not a gang failure —
                        # the death report above bumped the epoch and
                        # the survivors reshard (rendezvous/elastic);
                        # its nonzero code is returned, not raised
                        m.code = rc
                        self._event("death", m, {"code": rc,
                                                 "elastic": True})
                        continue
                    self._fail(m, rc,
                               budget_exhausted=(
                                   self.restart_policy is not None
                                   and m.role == "worker"))
                workers_done = all(m.code is not None
                                   for m in self.members
                                   if m.role == "worker")
                if workers_done and workers_done_at is None:
                    workers_done_at = time.monotonic()
                if workers_done_at is not None:
                    drain_at = workers_done_at + self.ps_grace_s
                    if deadline is not None:
                        # every worker succeeded: the grace must not
                        # push the drain past the launch timeout and
                        # turn a clean run into a misleading timeout
                        # failure (6s leaves the drain its own 5s
                        # terminate window)
                        drain_at = min(drain_at, deadline - 6.0)
                    if time.monotonic() >= drain_at:
                        self._drain_ps_roles()
                if all(m.code is not None for m in self.members):
                    break
                time.sleep(self.poll_interval_s)
            return [m.code for m in self.members]
        except BaseException:
            self._kill_all()
            raise
        finally:
            self._export_trace()
