"""dmlc_tpu.resilience — fault injection, retry policy, gang supervision.

The production story the reference dmlc-core tells with its ``recover``
handshake + ``DMLC_NUM_ATTEMPT`` rejoin (SURVEY §5.3), rebuilt as a
first-class subsystem over this repo's determinism contract
(docs/resilience.md):

- :mod:`~dmlc_tpu.resilience.policy` — declarative
  :class:`RetryPolicy` (attempts, exponential backoff + deterministic
  jitter, per-attempt timeout, retryable classifier, shared
  :class:`RetryBudget`), applied at named seams via :func:`guarded`;
  configured in code or via ``DMLC_TPU_RETRY``;
- :mod:`~dmlc_tpu.resilience.inject` — seeded, deterministic
  :class:`FaultPlan` (site glob × {ioerror, truncate, delay, crash} ×
  trigger) armed process-wide via ``DMLC_TPU_FAULTS``, firing inside
  the SAME seams the retries guard;
- :mod:`~dmlc_tpu.resilience.supervise` — :class:`GangSupervisor` +
  :class:`RestartPolicy`: ``launch_local(restart_policy=...)``
  restarts a dead worker with its same (part, num_parts, seed, epoch)
  coordinates and a bumped ``DMLC_TPU_ATTEMPT``, up to a budget,
  instead of killing the gang.

Every retry, injected fault, and restart is observable through
dmlc_tpu.obs: ``dmlc_resilience_retry_total`` /
``dmlc_resilience_fault_injected_total`` /
``dmlc_resilience_restart_total`` on /metrics, ``retry/<site>`` /
``fault/<site>`` / ``gang/restart/<member>`` trace instants, and a
``faults.json`` section in crash flight bundles.
"""

from dmlc_tpu.resilience import inject
from dmlc_tpu.resilience.inject import (
    CRASH_EXIT, ENV_FAULT_SEED, ENV_FAULTS, FaultClause, FaultPlan,
)
from dmlc_tpu.resilience.policy import (
    ENV_RETRY, AttemptTimeout, RetryBudget, RetryPolicy, default_policy,
    guarded, policy_for, reset_policies, retry_counts,
    set_default_policy, set_policy,
)
from dmlc_tpu.resilience.supervise import (
    ENV_ATTEMPT, GangMember, GangSupervisor, RestartPolicy,
)

__all__ = [
    "RetryPolicy", "RetryBudget", "AttemptTimeout", "guarded",
    "policy_for", "default_policy", "set_default_policy",
    "set_policy", "reset_policies", "retry_counts", "ENV_RETRY",
    "FaultPlan", "FaultClause", "inject", "ENV_FAULTS", "ENV_FAULT_SEED",
    "CRASH_EXIT",
    "RestartPolicy", "GangSupervisor", "GangMember", "ENV_ATTEMPT",
]
