"""Declarative retry/backoff policy — the repo's ONE retry mechanism.

Reference: the production dmlc-core survives worker faults through its
``recover`` handshake + ``DMLC_NUM_ATTEMPT`` rejoin (SURVEY §5.3); the
I/O layer's transient-error story there is ad-hoc per call site. Here
every retry in the repo flows through a :class:`RetryPolicy` applied at
a named **site** (``io.stream.open``, ``io.stream.read``,
``io.filesys.stat``, ``spill.commit``, ``checkpoint.save``,
``checkpoint.restore``, ``data.pages.build``, ``obs.scrape``), so

- attempts, exponential backoff + deterministic jitter, the
  retryable-exception classifier, an optional per-attempt timeout, and
  an optional :class:`RetryBudget` shared across a whole pipeline are
  POLICY, configured in one place (or via ``DMLC_TPU_RETRY``), not
  hand-rolled loops;
- every retry is observable: ``resilience.retry`` counter (rendered as
  ``dmlc_resilience_retry_total`` by obs/serve), per-site counts in the
  registered ``resilience`` collector, a ``retry/<site>`` trace
  instant, and a rate-limited obs.log warning.

The seam entry point is :func:`guarded`: near-zero cost on the quiet
path (one module-global read + try/except around the call), it engages
the site's policy only after a failure — and arms the
:mod:`~dmlc_tpu.resilience.inject` fault plane when a
:class:`FaultPlan` is installed, so chaos tests provoke the SAME retry
machinery real faults exercise. (Truncation faults act at the
byte-owning seam itself — ``io.stream.FileStream`` — which alone can
keep the stream position consistent with the shortened data.)

Env contract (``DMLC_TPU_RETRY``): ``;``-separated clauses of ``k=v``
pairs. A clause without ``site=`` overrides the global default; with
``site=<glob>`` it overrides matching sites. Keys: ``attempts``,
``base`` (seconds), ``max``, ``multiplier``, ``jitter`` (fraction),
``timeout`` (per-attempt seconds). Example::

    DMLC_TPU_RETRY="attempts=5,base=0.01;site=obs.scrape,attempts=1"
"""

from __future__ import annotations

import dataclasses
import fnmatch
import os
import random
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from dmlc_tpu.resilience import inject as _inject
from dmlc_tpu.utils.logging import DMLCError, check

__all__ = [
    "RetryPolicy", "RetryBudget", "AttemptTimeout",
    "guarded", "policy_for", "default_policy",
    "set_default_policy", "set_policy", "reset_policies",
    "retry_counts", "ENV_RETRY",
]

ENV_RETRY = "DMLC_TPU_RETRY"


class AttemptTimeout(TimeoutError):
    """A policed attempt exceeded ``attempt_timeout_s``. The worker
    thread running it is ABANDONED as a daemon (a last-resort guard
    for hung I/O, off by default) — and because TimeoutError is
    retryable by default, the next attempt may run WHILE the abandoned
    one is still blocked. Only set ``attempt_timeout_s`` on idempotent,
    state-free callables (none of the built-in seams set it: a shared
    fd touched by two unsynchronized attempts is corruption, not
    resilience)."""


class RetryBudget:
    """A shared, thread-safe pool of retries. Attach one budget to the
    policies of several sites (or one pipeline's whole seam set) and
    the TOTAL number of retries across them is bounded — a failing disk
    cannot turn a 10-stage pipeline into 10× max_attempts of backoff."""

    def __init__(self, max_retries: int):
        check(max_retries >= 0, "RetryBudget needs max_retries >= 0")
        self.max_retries = int(max_retries)
        self._lock = threading.Lock()
        self._spent = 0

    def take(self, site: str = "") -> bool:
        """Consume one retry; False when the budget is exhausted."""
        with self._lock:
            if self._spent >= self.max_retries:
                return False
            self._spent += 1
            return True

    @property
    def spent(self) -> int:
        return self._spent

    @property
    def remaining(self) -> int:
        return max(0, self.max_retries - self._spent)


def _default_retryable(exc: BaseException) -> bool:
    """Transient-I/O classifier: OSError-family errors retry, EXCEPT
    the ones that re-running cannot fix (missing file, permissions,
    wrong path shape). ValueError/DMLCError/etc. never retry — a parse
    error replayed is the same parse error."""
    if not isinstance(exc, (OSError, ConnectionError, TimeoutError)):
        return False
    return not isinstance(exc, (FileNotFoundError, PermissionError,
                                IsADirectoryError, NotADirectoryError,
                                FileExistsError))


@dataclass
class RetryPolicy:
    """Max attempts + exponential backoff with deterministic jitter.

    ``jitter`` is a ± fraction of the computed delay, derived from
    ``(jitter_seed, site, attempt)`` — deterministic, so a replayed
    fault plan produces the identical retry schedule (the same
    determinism contract the data plane keeps). ``sleep`` is
    injectable for tests. ``retryable`` may be a callable classifier
    or a tuple of exception types."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.1
    jitter_seed: int = 0x5EED
    attempt_timeout_s: Optional[float] = None
    retryable: Any = None           # callable | tuple[type] | None=default
    budget: Optional[RetryBudget] = None
    sleep: Callable[[float], None] = time.sleep

    def with_(self, **changes: Any) -> "RetryPolicy":
        return dataclasses.replace(self, **changes)

    # -- classification

    def is_retryable(self, exc: BaseException) -> bool:
        r = self.retryable
        if r is None:
            return _default_retryable(exc)
        if isinstance(r, (tuple, type)):
            return isinstance(exc, r)
        return bool(r(exc))

    # -- backoff

    def delay_for(self, site: str, attempt: int) -> float:
        d = min(self.max_delay_s,
                self.base_delay_s * self.multiplier ** (attempt - 1))
        if self.jitter:
            rng = random.Random(self.jitter_seed
                                ^ zlib.crc32(site.encode())
                                ^ (attempt * 0x9E3779B1))
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(0.0, d)

    # -- execution

    def _attempt(self, fn: Callable[[], Any]) -> Any:
        t = self.attempt_timeout_s
        if not t:
            return fn()
        box: List[Tuple[str, Any]] = []

        def run() -> None:
            try:
                box.append(("ok", fn()))
            except BaseException as e:  # noqa: BLE001 — relayed below
                box.append(("err", e))

        th = threading.Thread(target=run, daemon=True,
                              name="dmlc_tpu.resilience.attempt")
        th.start()
        th.join(t)
        if th.is_alive():
            raise AttemptTimeout(
                f"attempt exceeded {t}s (worker thread abandoned)")
        kind, val = box[0]
        if kind == "err":
            raise val
        return val

    def call(self, site: str, fn: Callable[[], Any],
             first_exc: Optional[BaseException] = None) -> Any:
        """Run ``fn`` under this policy. ``first_exc`` lets a fast-path
        caller (:func:`guarded`) hand over a failure it already took as
        attempt 1, so the quiet path pays no policy machinery."""
        attempt = 1
        exc = first_exc
        while True:
            if exc is not None:
                if not self.is_retryable(exc) \
                        or attempt >= self.max_attempts \
                        or (self.budget is not None
                            and not self.budget.take(site)):
                    raise exc
                delay = self.delay_for(site, attempt)
                _note_retry(site, attempt, exc, delay)
                self.sleep(delay)
                attempt += 1
                exc = None
            try:
                return self._attempt(fn)
            except Exception as e:  # noqa: BLE001 — classified above
                exc = e


# ------------------------------------------------------------ site registry

# built-in per-site CHANGES (applied over whatever the CURRENT default
# policy is at lookup time — a replaced default's sleep/backoff flows
# through); a gang scrape should fail fast: the unreachable rank is
# reported, not waited on through a full backoff ladder. The objstore
# peer tier retries a little HARDER than the default: a peer answering
# 404 is usually the block's owner still mid-hydration, and a few
# short waits are what let a non-owner pace itself behind the owner
# instead of double-fetching from the wire (it still degrades to the
# wire when the ladder runs out — never a hang).
_BUILTIN_SITE_DEFAULTS: List[Tuple[str, Dict[str, Any]]] = [
    ("obs.scrape", {"max_attempts": 2, "base_delay_s": 0.05}),
    ("io.objstore.peer", {"max_attempts": 4, "base_delay_s": 0.05,
                          "max_delay_s": 0.5}),
    # the write plane: one torn part of a multipart upload re-sends
    # just that part — retrying is much cheaper than aborting the
    # whole upload, so the ladder is a step deeper than the default
    ("io.objstore.put", {"max_attempts": 4, "base_delay_s": 0.05,
                         "max_delay_s": 0.5}),
    # membership ops (join/heartbeat/leave): a flaky connection must
    # be a counted retry, not a membership flap — the ladder stays
    # well inside the service's heartbeat grace window so retries
    # never masquerade as a missed beat
    ("rendezvous.*", {"max_attempts": 3, "base_delay_s": 0.05,
                      "max_delay_s": 0.3}),
]

_lock = threading.Lock()
_default: Optional[RetryPolicy] = None   # programmatic override
_prog_overrides: List[Tuple[str, RetryPolicy]] = []
_env_default_kv: Dict[str, str] = {}
_env_site_kv: List[Tuple[str, Dict[str, str]]] = []
_env_loaded = False
# True once ANY configured policy carries attempt_timeout_s: guarded()
# must then resolve the policy BEFORE attempt 1 so the hung-I/O guard
# can police the attempt most likely to hang (no built-in sets it, so
# the quiet fast path stays the default)
_timeout_configured = False


_ENV_KEYS = {"attempts": ("max_attempts", int),
             "base": ("base_delay_s", float),
             "max": ("max_delay_s", float),
             "multiplier": ("multiplier", float),
             "jitter": ("jitter", float),
             "timeout": ("attempt_timeout_s", float)}


def _policy_from_kv(kv: Dict[str, str],
                    base: RetryPolicy) -> RetryPolicy:
    changes: Dict[str, Any] = {}
    for key, val in kv.items():
        if key == "site":
            continue
        spec = _ENV_KEYS.get(key)
        if spec is None:
            raise DMLCError(
                f"{ENV_RETRY}: unknown key {key!r} "
                f"(known: {sorted(_ENV_KEYS)} + site)")
        field, conv = spec
        changes[field] = conv(val)
    return base.with_(**changes)


def _load_env_locked() -> None:
    global _env_loaded, _timeout_configured
    if _env_loaded:
        return
    _env_loaded = True
    for clause in os.environ.get(ENV_RETRY, "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        kv = _inject.parse_kv(clause, ENV_RETRY)
        if "timeout" in kv:
            _timeout_configured = True
        if "site" in kv:
            _env_site_kv.append((kv["site"], kv))
        else:
            _env_default_kv.update(kv)


def _default_locked() -> RetryPolicy:
    """The current default: programmatic override verbatim, else the
    built-in RetryPolicy with the env's global clause applied."""
    if _default is not None:
        return _default
    return _policy_from_kv(_env_default_kv, RetryPolicy())


def default_policy() -> RetryPolicy:
    with _lock:
        _load_env_locked()
        return _default_locked()


def set_default_policy(policy: RetryPolicy) -> None:
    """Replace the default policy. Env/built-in site overrides are
    stored as CHANGES and re-derived from the new default at lookup
    time, so an injected sleep or zeroed backoff reaches every site
    that only tweaks attempts."""
    global _default, _timeout_configured
    with _lock:
        _default = policy
        if policy.attempt_timeout_s:
            _timeout_configured = True


def set_policy(site_pattern: str, policy: RetryPolicy) -> None:
    """Override the policy for sites matching ``site_pattern`` (glob).
    Later calls outrank earlier ones and everything from the env."""
    global _timeout_configured
    with _lock:
        _prog_overrides.insert(0, (site_pattern, policy))
        if policy.attempt_timeout_s:
            _timeout_configured = True


def policy_for(site: str) -> RetryPolicy:
    with _lock:
        _load_env_locked()
        for pattern, policy in _prog_overrides:
            if fnmatch.fnmatchcase(site, pattern):
                return policy
        base = _default_locked()
        for pattern, kv in _env_site_kv:
            if fnmatch.fnmatchcase(site, pattern):
                return _policy_from_kv(kv, base)
        for pattern, changes in _BUILTIN_SITE_DEFAULTS:
            if fnmatch.fnmatchcase(site, pattern):
                return base.with_(**changes)
        return base


def reset_policies() -> None:
    """Forget programmatic + env-derived configuration (tests); the
    env is re-read on next use."""
    global _default, _env_loaded, _timeout_configured
    with _lock:
        _default = None
        _env_loaded = False
        _timeout_configured = False
        _prog_overrides.clear()
        _env_default_kv.clear()
        _env_site_kv.clear()
    with _counts_lock:
        _retry_counts.clear()


# ------------------------------------------------------------ observability

_counts_lock = threading.Lock()
_retry_counts: Dict[str, int] = {}


class _ResilienceStats:
    """Weakly-registerable owner of the per-site retry counts (plain
    dicts cannot carry a weakref)."""

    def snapshot(self) -> Dict[str, Any]:
        with _counts_lock:
            retry = dict(_retry_counts)
        return {"retry": retry,
                "faults_injected": _inject.injected_count()}


_stats = _ResilienceStats()
_stats_registered = False


def retry_counts() -> Dict[str, int]:
    """Per-site retry totals for this process (tests/diagnostics)."""
    with _counts_lock:
        return dict(_retry_counts)


def _note_retry(site: str, attempt: int, exc: BaseException,
                delay: float) -> None:
    global _stats_registered
    with _counts_lock:
        _retry_counts[site] = _retry_counts.get(site, 0) + 1
    try:
        from dmlc_tpu.obs.metrics import REGISTRY
        if not _stats_registered:
            _stats_registered = True
            REGISTRY.register("resilience", _stats,
                              _ResilienceStats.snapshot)
        REGISTRY.counter("resilience.retry").inc()
        from dmlc_tpu.obs import trace
        trace.instant(f"retry/{site}", "resilience",
                      {"attempt": attempt, "delay_s": round(delay, 4),
                       "error": repr(exc)[:200]})
        from dmlc_tpu.obs.log import warn_limited
        warn_limited(
            f"retry-{site}",
            f"resilience: {site} failed ({exc!r}); retrying "
            f"(attempt {attempt} -> {attempt + 1}, {delay:.3f}s backoff)",
            min_interval_s=60.0, all_ranks=True)
    except Exception:  # noqa: BLE001 — telemetry must never block a retry
        pass


# ------------------------------------------------------------ seam helpers

def guarded(site: str, fn: Callable[[], Any],
            policy: Optional[RetryPolicy] = None) -> Any:
    """THE seam entry point: run ``fn`` under ``site``'s retry policy,
    firing any armed fault plan inside each attempt.

    Quiet-path cost (no plan armed, no explicit/configured policy that
    needs to police attempt 1, first attempt succeeds): one
    module-global read + try/except + the call — cheap enough for
    per-chunk reads. The policy machinery engages up-front whenever
    any configured policy carries ``attempt_timeout_s`` (the hung-I/O
    guard must police the FIRST attempt — the one most likely to
    hang), otherwise only on failure."""
    if not _env_loaded:
        # a timeout configured ONLY via DMLC_TPU_RETRY must be seen
        # BEFORE the first fast-path call, not at first failure — a
        # hung first read would otherwise never meet its guard
        with _lock:
            _load_env_locked()
    plan = _inject._plan
    if plan is None and policy is None and not _timeout_configured:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classified by policy
            pol = policy_for(site)
            if not pol.is_retryable(e):
                raise
            return pol.call(site, fn, first_exc=e)
    pol = policy if policy is not None else policy_for(site)
    if plan is None:
        return pol.call(site, fn)

    def attempt() -> Any:
        live = _inject._plan
        if live is not None:
            try:
                live.fire(site)
            except BaseException:
                # the fault killed the attempt before its transport
                # opened a client span — tell the tracing plane so the
                # retry still shows as one countable span per attempt
                from dmlc_tpu.obs import rpc as _rpc
                _rpc.note_injected_failure(site)
                raise
        return fn()

    return pol.call(site, attempt)
