"""Seeded, deterministic fault-injection plane.

Chaos that cannot be replayed cannot be debugged. A :class:`FaultPlan`
is a list of clauses — site pattern × fault type × trigger — armed
process-wide via :func:`install` (or :func:`install_if_env` under the
``DMLC_TPU_FAULTS`` env contract, which ``launch_local(faults=...)``
sets for every gang member, so a multi-process gang provokes IDENTICAL
failures on every run). The instrumented seams
(:func:`dmlc_tpu.resilience.policy.guarded` call sites) fire the plan
inside every retried attempt, so a ``times=2`` clause exercises exactly
"fail twice, then succeed".

Clause grammar (``;``-separated clauses of ``,``-separated ``k=v``)::

    DMLC_TPU_FAULTS="site=io.stream.read,fault=ioerror,times=2;
                     site=bench.block,fault=crash,nth=3,rank=1,attempt=0"

- ``site=<glob>``   (required) — fnmatch pattern over seam site names;
- ``fault=<type>``  (required) — ``ioerror`` (raise IOError),
  ``truncate`` (corrupt returned read bytes: drop the tail half),
  ``delay`` (sleep ``delay_s``), ``crash`` (dump a flight bundle if a
  recorder is installed, then ``os._exit(CRASH_EXIT)`` — a hard,
  no-cleanup death);
- trigger (at most one) — ``times=N`` (first N armed matches),
  ``nth=K`` (exactly the K-th), ``p=F`` (each match with probability
  F from a seeded RNG: same seed ⇒ same fault sequence); no trigger =
  every match;
- scoping — ``rank=K`` (only the gang member with that
  ``DMLC_TPU_TASK_ID``), ``attempt=K`` (only that restart attempt,
  ``DMLC_TPU_ATTEMPT``; how "crash once, run clean after the
  supervisor restarts me" is expressed);
- ``delay_s=X`` (for ``fault=delay``), ``seed=S`` (per-clause RNG
  seed override; the plan seed ``DMLC_TPU_FAULT_SEED`` is the base).

Every injected fault is observable: ``resilience.fault.injected``
counter, a ``fault/<site>`` trace instant, and the plan's bounded
event log — which the crash flight recorder copies into its bundle
(``faults.json``), so a post-mortem states what chaos was armed.
"""

from __future__ import annotations

import fnmatch
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from dmlc_tpu.utils.logging import DMLCError, check

__all__ = [
    "FaultClause", "FaultPlan", "install", "uninstall", "active",
    "install_if_env", "fire", "corrupt", "injected_count", "parse_kv",
    "ENV_FAULTS", "ENV_FAULT_SEED", "CRASH_EXIT",
]

ENV_FAULTS = "DMLC_TPU_FAULTS"
ENV_FAULT_SEED = "DMLC_TPU_FAULT_SEED"
# the env the gang supervisor bumps on every restart (reference:
# DMLC_NUM_ATTEMPT, accepted as an alias)
_ENV_ATTEMPT = "DMLC_TPU_ATTEMPT"
FAULT_TYPES = ("ioerror", "truncate", "delay", "crash")
CRASH_EXIT = 77  # distinctive nonzero exit of an injected crash

_EVENT_LOG_CAP = 512


@dataclass
class FaultClause:
    site: str
    fault: str
    times: Optional[int] = None
    nth: Optional[int] = None
    p: Optional[float] = None
    delay_s: float = 0.05
    rank: Optional[int] = None
    attempt: Optional[int] = None
    seed: Optional[int] = None

    def __post_init__(self) -> None:
        check(self.fault in FAULT_TYPES,
              f"unknown fault type {self.fault!r} (known: {FAULT_TYPES})")
        check(sum(x is not None for x in (self.times, self.nth, self.p))
              <= 1, "at most one trigger of times=/nth=/p= per clause")

    def spec(self) -> str:
        parts = [f"site={self.site}", f"fault={self.fault}"]
        for key in ("times", "nth", "p", "rank", "attempt", "seed"):
            v = getattr(self, key)
            if v is not None:
                parts.append(f"{key}={v}")
        if self.fault == "delay":
            parts.append(f"delay_s={self.delay_s}")
        return ",".join(parts)


_CLAUSE_KEYS = {"times": int, "nth": int, "p": float, "rank": int,
                "attempt": int, "seed": int, "delay_s": float}


def parse_kv(text: str, label: str) -> Dict[str, str]:
    """One ``,``-separated ``k=v`` clause -> dict. The ONE parser for
    the resilience env grammars (DMLC_TPU_FAULTS and DMLC_TPU_RETRY
    share it, so the clause syntax cannot drift between them)."""
    out: Dict[str, str] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        check("=" in part, f"{label}: expected k=v, got {part!r}")
        k, _, v = part.partition("=")
        out[k.strip()] = v.strip()
    return out


def _parse_clause(text: str) -> FaultClause:
    kv = parse_kv(text, ENV_FAULTS)
    check("site" in kv and "fault" in kv,
          f"{ENV_FAULTS}: clause needs site= and fault= ({text!r})")
    args: Dict[str, Any] = {"site": kv.pop("site"),
                            "fault": kv.pop("fault")}
    for key, val in kv.items():
        conv = _CLAUSE_KEYS.get(key)
        if conv is None:
            raise DMLCError(f"{ENV_FAULTS}: unknown key {key!r} "
                            f"(known: {sorted(_CLAUSE_KEYS)})")
        args[key] = conv(val)
    return FaultClause(**args)


class FaultPlan:
    """An armed set of clauses with deterministic per-clause state."""

    def __init__(self, clauses: List[FaultClause], seed: int = 0):
        check(len(clauses) >= 1, "FaultPlan needs at least one clause")
        self.clauses = list(clauses)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._counts = [0] * len(self.clauses)
        self._rngs = [random.Random(
            (c.seed if c.seed is not None else self.seed) * 1000003 + i)
            for i, c in enumerate(self.clauses)]
        self._events: List[Dict[str, Any]] = []
        self.injected = 0
        # rank/attempt are fixed for the process's lifetime: cache them
        self._rank = self._int_env("DMLC_TPU_TASK_ID", "DMLC_TASK_ID")
        self._attempt = self._int_env(_ENV_ATTEMPT,
                                      "DMLC_NUM_ATTEMPT") or 0

    @staticmethod
    def _int_env(*names: str) -> Optional[int]:
        for name in names:
            v = os.environ.get(name)
            if v is not None:
                try:
                    return int(v)
                except ValueError:
                    pass
        return None

    @classmethod
    def parse(cls, spec: str, seed: Optional[int] = None) -> "FaultPlan":
        clauses = [_parse_clause(c) for c in spec.split(";")
                   if c.strip()]
        if seed is None:
            seed = int(os.environ.get(ENV_FAULT_SEED, "0") or "0")
        return cls(clauses, seed=seed)

    def spec(self) -> str:
        return ";".join(c.spec() for c in self.clauses)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    # -- matching

    def _scoped(self, clause: FaultClause, site: str) -> bool:
        if not fnmatch.fnmatchcase(site, clause.site):
            return False
        if clause.rank is not None and (self._rank or 0) != clause.rank:
            return False
        if clause.attempt is not None and self._attempt != clause.attempt:
            return False
        return True

    def _triggered(self, i: int, clause: FaultClause) -> bool:
        """Per-clause trigger check; caller holds no lock."""
        with self._lock:
            self._counts[i] += 1
            n = self._counts[i]
            if clause.nth is not None:
                return n == clause.nth
            if clause.times is not None:
                return n <= clause.times
            if clause.p is not None:
                return self._rngs[i].random() < clause.p
            return True

    def _record(self, site: str, clause: FaultClause) -> None:
        ev = {"site": site, "fault": clause.fault,
              "clause": clause.spec(), "time": time.time()}
        with self._lock:
            self.injected += 1
            ev["seq"] = self.injected
            if len(self._events) < _EVENT_LOG_CAP:
                self._events.append(ev)
        try:
            from dmlc_tpu.obs.metrics import REGISTRY
            REGISTRY.counter("resilience.fault.injected").inc()
            from dmlc_tpu.obs import trace
            trace.instant(f"fault/{site}", "resilience",
                          {"fault": clause.fault,
                           "clause": clause.spec()})
        except Exception:  # noqa: BLE001 — telemetry must not mask chaos
            pass

    # -- firing

    def fire(self, site: str) -> None:
        """Apply raising/delaying/crashing clauses armed at ``site``
        (truncation acts in :meth:`corrupt` — it needs the data)."""
        for i, clause in enumerate(self.clauses):
            if clause.fault == "truncate" or not self._scoped(clause, site):
                continue
            if not self._triggered(i, clause):
                continue
            self._record(site, clause)
            if clause.fault == "delay":
                time.sleep(clause.delay_s)
            elif clause.fault == "ioerror":
                raise IOError(
                    f"injected fault at site {site!r} ({clause.spec()})")
            elif clause.fault == "crash":
                self._crash(site, clause)

    def _crash(self, site: str, clause: FaultClause) -> None:
        """Hard death: the flight recorder (if installed) gets one dump
        — os._exit runs no atexit hooks, by design (a crashed worker
        flushes nothing, exactly what supervision must survive)."""
        try:
            from dmlc_tpu.obs import flight
            fl = flight.active()
            if fl is not None:
                fl.dump("injected_crash")
        except Exception:  # noqa: BLE001 — the crash must still happen
            pass
        os._exit(CRASH_EXIT)

    def has_truncate(self, site: str) -> bool:
        """Whether ANY truncate clause is scoped at ``site`` (no
        trigger counters consumed): lets byte-owning seams skip the
        payload materialization :meth:`corrupt` needs when no armed
        clause could ever shorten it."""
        return any(c.fault == "truncate" and self._scoped(c, site)
                   for c in self.clauses)

    def corrupt(self, site: str, data: bytes) -> bytes:
        """Apply armed truncation clauses to returned read bytes: drop
        the tail half (>=1 byte for non-empty data), simulating a torn
        read/short object the downstream framing must detect. The
        byte-owning seam (io.stream.FileStream) also pins its stream
        at EOF when this shortens data — without that, the advanced
        file position would shift later bytes into the hole and
        fixed-size readers would load silently wrong payloads."""
        if not data:
            return data
        for i, clause in enumerate(self.clauses):
            if clause.fault != "truncate" or not self._scoped(clause, site):
                continue
            if not self._triggered(i, clause):
                continue
            self._record(site, clause)
            data = data[:len(data) // 2]
        return data


# ------------------------------------------------------------ module plane

# THE armed plan (None = chaos off). Seams read this one global via
# policy.guarded's fast path; keep it a plain module attribute.
_plan: Optional[FaultPlan] = None


def active() -> Optional[FaultPlan]:
    return _plan


def install(plan: "FaultPlan | str",
            seed: Optional[int] = None) -> FaultPlan:
    """Arm ``plan`` (a FaultPlan or a spec string) process-wide,
    replacing any armed predecessor."""
    global _plan
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan, seed=seed)
    _plan = plan
    return plan


def uninstall() -> Optional[FaultPlan]:
    global _plan
    plan, _plan = _plan, None
    return plan


def install_if_env() -> Optional[FaultPlan]:
    """Gang-worker hook (one line, like trace_if_env): arm the fault
    plan when ``DMLC_TPU_FAULTS`` is set — ``launch_local(faults=...)``
    sets it for every member — else no-op."""
    spec = os.environ.get(ENV_FAULTS)
    if not spec:
        return None
    return install(spec)


def fire(site: str) -> None:
    """Public site arming for code outside the built-in seams (e.g. a
    worker loop arming its own per-block site). No-op when chaos is
    off; one global read."""
    plan = _plan
    if plan is not None:
        plan.fire(site)


def corrupt(site: str, data: bytes) -> bytes:
    plan = _plan
    if plan is not None:
        return plan.corrupt(site, data)
    return data


def injected_count() -> int:
    plan = _plan
    return plan.injected if plan is not None else 0
