"""Worker for bench_suite config 7 (multi-process ingest throughput).

Run under parallel.launch_local as a REAL 2-process jax.distributed
gang: each process joins the rendezvous, streams its device-granular
shards of a criteo-shaped libsvm file through ShardedRowBlockIter for
three epochs, and writes per-epoch wall times. Epoch 1 parses AND
carries the one-time round-count agreement (ONE allgather via the
cached counting pass, VERDICT r3 #6) — first_epoch_gbps is therefore
the PARSE-path rate. Epochs 2+ run collective-free (VERDICT r2 #3)
and, since r5, serve the retained stacked rounds from memory
(steady-epoch REPLAY, VERDICT r4 #2): the steady gbps is the
repeated-epoch training cadence, not a re-parse rate — compare it to
first_epoch_gbps for the replay speedup, and to pre-r5 config-7
numbers only via first_epoch_gbps. replay_epochs in the output records
that the replay path actually served.

Usage: bench_mp_worker.py <data_uri> <out_dir>
"""

import json
import os
import sys
import time

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the axon TPU plugin overrides the env var; the config update is
    # authoritative (same dance as tests/conftest.py)
    import jax
    jax.config.update("jax_platforms", "cpu")


def main() -> int:
    # live-telemetry opt-ins (each a no-op without its env var): the
    # per-rank status server under launch_local(serve_ports=...), the
    # crash flight recorder under launch_local(flight_dir=...), and the
    # rank-tagged gang trace under launch_local(trace_dir=...)
    from dmlc_tpu.obs.aggregate import install_if_env as gang_if_env
    from dmlc_tpu.obs.flight import install_if_env
    from dmlc_tpu.obs.profile import install_if_env as prof_if_env
    from dmlc_tpu.obs.serve import serve_if_env
    from dmlc_tpu.obs.slo import install_if_env as slo_if_env
    from dmlc_tpu.obs.timeseries import install_if_env as hist_if_env
    from dmlc_tpu.obs.trace import trace_if_env
    from dmlc_tpu.pipeline.scheduler import install_if_env as sched_if_env
    from dmlc_tpu.rendezvous import install_if_env as rndv_if_env
    serve_if_env()
    rndv_if_env()     # DMLC_TPU_RNDV_URI/PORT: elastic membership
    sched_if_env()    # DMLC_TPU_SCHED: multi-tenant scheduler
    slo_if_env()      # DMLC_TPU_SLO: declared objectives on /slo
    hist_if_env()     # before flight: DMLC_TPU_HISTORY_S must win
    install_if_env()
    gang_if_env()     # DMLC_TPU_GANG_POLL_S (rank 0 only): /gang
    prof_if_env()     # DMLC_TPU_PROFILE_HZ: /profile flamegraphs
    with trace_if_env():
        return _run()


def _run() -> int:
    data_uri, out_dir = sys.argv[1], sys.argv[2]
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from dmlc_tpu.parallel.launch import init_from_env, finalize
    from dmlc_tpu.parallel.sharded import ShardedRowBlockIter

    pid, nprocs = init_from_env()
    # warm the host-collective machinery (XLA compile of the tiny
    # allgather program — paid once per process by ANY collective
    # JAX program): epoch-1 timing should measure the ingest protocol,
    # not a constant compile that real jobs amortize to zero
    if nprocs > 1:
        from jax.experimental import multihost_utils
        multihost_utils.process_allgather(np.zeros(2, np.int64))
    mesh = Mesh(np.array(jax.devices()), ("data",))
    it = ShardedRowBlockIter(data_uri, mesh, format="libsvm",
                             row_bucket=1 << 11, nnz_bucket=1 << 16,
                             chunk_size=4 << 20)
    epoch_walls = []
    nbatches = 0
    for _ in range(3):
        t0 = time.perf_counter()
        n = 0
        for batch in it:
            jax.block_until_ready(batch["value"])
            n += 1
        epoch_walls.append(time.perf_counter() - t0)
        nbatches = n
    with open(os.path.join(out_dir, f"bench-mp-{pid}.json"), "w") as f:
        json.dump({"rank": pid, "world": nprocs, "batches": nbatches,
                   "epoch_walls": epoch_walls,
                   # epochs 2-3 should serve from the retained rounds
                   # (steady replay, VERDICT r4 #2); r6 adds which TIER
                   # served (memory within budget / pages above it)
                   "replay_epochs": it.replay_epochs,
                   "page_replay_epochs": it.page_replay_epochs,
                   "replay_tier": it.replay_tier}, f)
    finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
