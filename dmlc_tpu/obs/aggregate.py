"""Gang-wide metrics aggregation: one timeline across every rank.

``scrape_gang()`` (PR 4) merges the gang's CURRENT snapshots once. The
analysis plane needs the gang OVER TIME: a rank-0 (or launcher-side)
:class:`GangAggregator` polls every rank's ``/metrics.json`` — through
the existing ``obs.scrape`` resilience seam, so one dropped connection
does not mark a live rank unreachable — and merges the polls onto one
wall-anchored timeline:

- **per-rank series**: each member feeds its own
  :class:`~dmlc_tpu.obs.timeseries.TimeSeriesRing` (same coarsening
  mechanics, same byte budget each), so a 2-hour gang run fits the
  same memory as a 10-second one;
- **rollups**: at every poll, sum/min/max across the REACHABLE ranks
  per numeric series, plus ``gang.reachable``/``gang.expected`` so a
  reader can see membership shrink on the same timeline;
- **explicit gaps**: an unreachable rank gets a gap marker (poll time
  + error) instead of an interpolated value — the rank you cannot
  scrape is exactly the one you are diagnosing, and inventing numbers
  for it would hide the outage the timeline exists to show.

Installed on rank 0 via ``launch_local(gang_poll_s=...)`` →
``DMLC_TPU_GANG_POLL_S`` (+ the PR-4 ``DMLC_TPU_SERVE_PORTS`` gang
list); workers opt in with one :func:`install_if_env` call. The live
view serves as ``GET /gang`` on the member's StatusServer.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from dmlc_tpu.obs.timeseries import TimeSeriesRing, numeric_leaves

__all__ = ["GangAggregator", "install", "uninstall", "active",
           "install_if_env", "ENV_GANG_POLL_S", "GANG_SCHEMA"]

# bump when view()'s top-level shape changes incompatibly
GANG_SCHEMA = 1

ENV_GANG_POLL_S = "DMLC_TPU_GANG_POLL_S"

# bounded per-member gap log: a rank that stays dead for hours must
# not grow the view without bound — the FIRST gap after each outage
# transition plus the most recent ones tell the whole story
MAX_GAPS = 64

# knob VALUES are identities, not quantities — summing rank 0's queue
# depth with rank 1's reads as nonsense on the rollup timeline (the
# per-rank series still carry them; obsctl gang reads those).
# Control collectors may be name-suffixed ("control#2" when two
# controllers coexist), so their knob leaves are matched by the pair
# below, not a plain prefix. SLO rows are ratios/specs — summing
# attainments across ranks is meaningless; the dedicated merged
# ``slo`` section on view() carries the count-level merge instead.
_ROLLUP_SKIP_SECTIONS = ("collectors.pipeline.knobs",
                         "collectors.slo")
_ROLLUP_SKIP_PAIRS = (("collectors.control", ".knobs."),)


class _Member:
    """One gang member's aggregation state (keyed by serve port)."""

    __slots__ = ("port", "rank", "ring", "gaps", "unreachable",
                 "last_error", "last_poll_t", "polls_ok", "polls_failed",
                 "last_rpc", "last_slo")

    def __init__(self, port: int, budget_bytes: int, period_s: float):
        self.port = port
        self.rank: Optional[int] = None
        self.ring = TimeSeriesRing(period_s=period_s,
                                   budget_bytes=budget_bytes)
        self.gaps: List[Dict[str, Any]] = []
        self.unreachable = False
        self.last_error: Optional[str] = None
        self.last_poll_t: Optional[float] = None
        self.polls_ok = 0
        self.polls_failed = 0
        # the rank's last-scraped RPC edge totals (obs.rpc collector):
        # /gang carries the gang-wide wire-attribution picture
        self.last_rpc: Optional[Dict[str, Any]] = None
        # the rank's last-scraped SLO objective rows (obs.slo
        # collector): rank 0 judges gang objectives on merged counts
        self.last_slo: Optional[Dict[str, Any]] = None

    def label(self) -> str:
        return (f"rank{self.rank}" if self.rank is not None
                else f"port{self.port}")


class GangAggregator:
    """Poll the gang; keep per-rank history, rollups, explicit gaps."""

    def __init__(self, ports: Optional[List[int]] = None,
                 host: str = "127.0.0.1",
                 period_s: float = 2.0,
                 timeout_s: float = 2.0,
                 budget_bytes: int = 128 << 10):
        if ports is None:
            from dmlc_tpu.obs.serve import ENV_SERVE_PORTS
            raw = os.environ.get(ENV_SERVE_PORTS, "")
            ports = [int(p) for p in raw.split(",") if p.strip()]
        self.ports = list(ports)
        self.host = host
        self.period_s = max(0.05, float(period_s))
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._members = {p: _Member(p, budget_bytes, self.period_s)
                         for p in self.ports}
        self._rollup = TimeSeriesRing(period_s=self.period_s,
                                      budget_bytes=budget_bytes)
        self._polls = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- polling

    def poll_once(self, t: Optional[float] = None) -> Dict[str, Any]:
        """One poll pass over every port; returns {label: ok_bool}.
        Wall-anchored: every member's sample from this pass shares one
        timestamp, so cross-rank reads line up by construction."""
        from dmlc_tpu.obs.serve import scrape
        t = time.time() if t is None else t
        reachable: List[Dict[str, float]] = []
        status: Dict[str, bool] = {}
        for port in self.ports:
            m = self._members[port]
            try:
                snap = scrape(port, host=self.host,
                              timeout_s=self.timeout_s)
                leaves = numeric_leaves(snap)
            except Exception as e:  # noqa: BLE001 — dead rank: a GAP,
                with self._lock:    # never an invented sample
                    m.polls_failed += 1
                    m.last_error = repr(e)
                    m.last_poll_t = t
                    # log the transition INTO the outage and a bounded
                    # tail of the outage's polls; the earliest gap (when
                    # the outage began) always survives the pruning
                    m.gaps.append({"t": t, "error": repr(e),
                                   "first": not m.unreachable})
                    if len(m.gaps) > MAX_GAPS:
                        m.gaps = m.gaps[:1] + m.gaps[-(MAX_GAPS - 1):]
                    m.unreachable = True
                status[m.label()] = False
                continue
            with self._lock:
                if snap.get("rank") is not None:
                    m.rank = snap["rank"]
                m.polls_ok += 1
                m.unreachable = False
                m.last_error = None
                m.last_poll_t = t
                rpc = (snap.get("collectors") or {}).get("rpc")
                if isinstance(rpc, dict):
                    m.last_rpc = rpc
                slo = (snap.get("collectors") or {}).get("slo")
                if isinstance(slo, dict):
                    m.last_slo = slo
            m.ring.append(t, leaves)
            reachable.append(leaves)
            status[m.label()] = True
        self._rollup.append(t, self._rollup_leaves(reachable))
        with self._lock:
            self._polls += 1
        return status

    def _rollup_leaves(self, per_rank: List[Dict[str, float]]
                       ) -> Dict[str, float]:
        """sum/min/max across the reachable ranks per series — NOT
        across time (the rings own time)."""
        out: Dict[str, float] = {
            "gang.expected": float(len(self.ports)),
            "gang.reachable": float(len(per_rank)),
        }
        keys: set = set()
        for leaves in per_rank:
            keys.update(leaves)
        for key in keys:
            if key.startswith(_ROLLUP_SKIP_SECTIONS):
                continue
            if any(key.startswith(p) and mid in key
                   for p, mid in _ROLLUP_SKIP_PAIRS):
                continue
            vals = [lv[key] for lv in per_rank if key in lv]
            if not vals:
                continue
            out[f"sum.{key}"] = sum(vals)
            out[f"min.{key}"] = min(vals)
            out[f"max.{key}"] = max(vals)
        return out

    # -- reads

    def view(self, last_s: Optional[float] = None) -> Dict[str, Any]:
        """The /gang payload: per-member series + gaps + reachability,
        and the gang rollup timeline."""
        with self._lock:
            members = list(self._members.values())
            polls = self._polls
        ranks: Dict[str, Any] = {}
        for m in members:
            ranks[m.label()] = {
                "port": m.port,
                "rank": m.rank,
                "unreachable": m.unreachable,
                "last_error": m.last_error,
                "last_poll_t": m.last_poll_t,
                "polls_ok": m.polls_ok,
                "polls_failed": m.polls_failed,
                "gaps": list(m.gaps),
                "rpc": m.last_rpc,
                "slo": m.last_slo,
                "series": m.ring.to_dict(last_s=last_s),
            }
        out = {
            "schema": GANG_SCHEMA,
            "period_s": self.period_s,
            "host": self.host,
            "ports": list(self.ports),
            "polls": polls,
            "ranks": ranks,
            "rollup": self._rollup.to_dict(last_s=last_s),
        }
        slo_views = [m.last_slo for m in members
                     if isinstance(m.last_slo, dict)]
        if slo_views:
            # gang-level objectives judged on MERGED window counts;
            # unreachable ranks flag the section incomplete rather
            # than silently skewing the attainment
            try:
                from dmlc_tpu.obs import slo as _slo
                out["slo"] = _slo.merge_views(
                    slo_views,
                    unreachable=[m.label() for m in members
                                 if m.unreachable])
            except Exception:  # noqa: BLE001 — rollup must not kill
                pass           # the /gang read
        return out

    # -- lifecycle

    def start(self) -> "GangAggregator":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="dmlc_tpu.obs.GangAggregator")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the poll loop survives
                pass


_aggregator: Optional[GangAggregator] = None


def active() -> Optional[GangAggregator]:
    return _aggregator


def install(ports: Optional[List[int]] = None,
            **kwargs: Any) -> GangAggregator:
    """Install + start the process gang aggregator (idempotent)."""
    global _aggregator
    if _aggregator is not None:
        return _aggregator
    _aggregator = GangAggregator(ports=ports, **kwargs).start()
    return _aggregator


def uninstall() -> None:
    global _aggregator
    agg, _aggregator = _aggregator, None
    if agg is not None:
        agg.stop()


def install_if_env() -> Optional[GangAggregator]:
    """Gang-worker hook (one line, like serve_if_env): start the gang
    aggregator when ``DMLC_TPU_GANG_POLL_S`` is set —
    ``launch_local(gang_poll_s=...)`` sets it on RANK 0 only — with the
    gang's ports from ``DMLC_TPU_SERVE_PORTS``; else no-op."""
    raw = os.environ.get(ENV_GANG_POLL_S)
    if not raw:
        return None
    try:
        period = float(raw)
    except ValueError as e:
        from dmlc_tpu.obs.log import warn_once
        warn_once("gang-poll-env-failed",
                  f"obs.aggregate: bad {ENV_GANG_POLL_S}={raw!r}: {e}",
                  all_ranks=True)
        return None
    agg = install(period_s=period)
    if not agg.ports:
        from dmlc_tpu.obs.log import warn_once
        warn_once("gang-poll-no-ports",
                  "obs.aggregate: DMLC_TPU_GANG_POLL_S set but no "
                  "DMLC_TPU_SERVE_PORTS gang list to poll",
                  all_ranks=True)
    return agg
