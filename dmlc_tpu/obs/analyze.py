"""Bottleneck attribution: turn recorded telemetry into a verdict.

The BENCH r1→r7 trajectory (0.34 → 0.58 sustained GB/s) has been
interpreted by a human reading JSON files: stage waits here, credit
gauges there, assembly path in a third key. This module is that
judgment as code, decomposing an epoch from data the plane ALREADY
records — ``StageProbe`` wait totals, the device stage's
``xfer_wait_s``/``staging_assemble_s`` extras, the fused engine's
``assemble_s``, pagestore/objstore hit counters, and the credit-gauge
bands bench.py computes — into one structured verdict:

``{"schema": 4, "epoch": <monotonic>, "verdict_id": "v<epoch>-<digest>",
"tenant": <label or None>, "bound": "parse" | "assemble" | "xfer" |
"wire" | "credit-limited" | "consumer", "band": <credit band>,
"confidence": "high" | "medium" | "low", "evidence": [...],
"hot_frames": [...], "stage_waits": {...}}``

``tenant`` (schema 4) is the multi-tenant label: a pipeline admitted
under a :mod:`dmlc_tpu.pipeline.scheduler` tenant stamps its epoch
snapshots with the tenant name, so the verdict says WHOSE epoch it
judged — the ``/tenants`` rows cite a per-tenant bound, and the
controller's ledger records inherit it through the verdict. None for
untenanted pipelines.

``epoch``/``verdict_id`` (schema 3) make verdicts citable: the epoch
is the snapshot's monotonic counter and the id digests what was
judged, so a control-plane ledger record (:mod:`dmlc_tpu.obs.control`)
can reference the EXACT verdict that moved a knob.

``hot_frames`` (schema 2) is function-level evidence from the
sampling profiler (:mod:`dmlc_tpu.obs.profile`) when one is
installed: the top on-CPU frames whose call path matches the bound
component — the first rung below stage granularity, "parse-bound"
becomes "parse-bound, and it is THIS function". Empty when no
profiler runs (the verdict says which stage, not which frame).

The key set is pinned by ``scripts/lint.py``'s verdict-schema gate (a
literal-dict key check like the metric-name gate), so the ``/analyze``
endpoint, bench.py's embedded ``"analysis"`` block, and
``scripts/obsctl.py`` can never drift apart. Every evidence entry
names the MEASURED quantity it rests on — two legs with different
stage waits can share a ``bound`` but never share evidence.

The second half is regression judgment: :func:`compare` diffs two
BENCH JSONs band-for-band (BASELINE.md's credit-recovery bands), so
in-band credit variance — the ~10x wall-rate swing this burstable
host's credit scheduler causes — is reported as variance, and only an
out-of-tolerance delta WITHIN one comparability band flags as a
regression.
"""

from __future__ import annotations

import hashlib as _hashlib
import json
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["attribute", "slo_verdict", "compare", "compare_files",
           "load_bench", "diagnose_bench", "gauge_band",
           "VERDICT_KEYS", "BOUNDS", "ANALYSIS_SCHEMA",
           "DEFAULT_TOLERANCE"]

# bump when the verdict's top-level shape changes incompatibly
# (2: hot_frames — sampling-profiler function-level evidence;
#  3: epoch + verdict_id — the control ledger back-references the
#  exact verdict that moved a knob;
#  4: tenant — multi-tenant snapshots carry a tenant label, so a
#  verdict says WHOSE epoch it judged and the /tenants rows can cite
#  a per-tenant bound; None for untenanted pipelines)
ANALYSIS_SCHEMA = 4

# the verdict's pinned key set — scripts/lint.py's verdict-schema gate
# checks every literal verdict dict in the package against this tuple
VERDICT_KEYS = ("schema", "epoch", "verdict_id", "tenant", "bound",
                "band", "confidence", "evidence", "hot_frames",
                "stage_waits")

BOUNDS = ("parse", "assemble", "xfer", "wire", "credit-limited",
          "consumer",
          # a declared objective burning its error budget (obs.slo) —
          # not a stage, but it rides the same verdict contract so the
          # Controller can consume it without a second shape
          "slo")

# in-band delta tolerated before compare() flags a regression: the
# BENCH_r0* archive shows ~±12% sustained-rate spread across same-band
# reruns with no code change (credit/thermal climate), so the default
# sits just above it
DEFAULT_TOLERANCE = 0.15

# below this fraction of wall spent waiting on ANY stage, the pipeline
# is not the bottleneck — whoever consumes it is
_CONSUMER_WAIT_FRAC = 0.15


def gauge_band(g: Optional[float]) -> str:
    """Credit-comparability band of one host-memcpy gauge reading
    (BASELINE.md "Credit-recovery profile"). The ONE implementation —
    bench.py and compare() both read bands through here."""
    if g is None:
        return "unknown"
    if g < 1.0:
        return "drained"
    if g < 1.6:
        return "plateau"
    if g < 3.0:
        return "elevated"
    return "full"


def _modal_band(gauges: Optional[List[float]]) -> str:
    if not gauges:
        return "unknown"
    counts: Dict[str, int] = {}
    for g in gauges:
        b = gauge_band(g)
        counts[b] = counts.get(b, 0) + 1
    return max(counts, key=lambda b: counts[b])


def _counter(metrics: Optional[Dict[str, Any]], name: str) -> float:
    if not metrics:
        return 0.0
    v = (metrics.get("counters") or {}).get(name)
    return float(v) if isinstance(v, (int, float)) else 0.0


# call-path substrings that tie a sampled frame to a bound component:
# hot_frames for bound=X keeps frames whose path matches X's hints
# (falling back to the overall top when nothing matches — an honest
# "hottest frames overall" beats fabricated stage attribution)
_BOUND_FRAME_HINTS = {
    "parse": ("native:parse", "native:read", "parser", "parse",
              "tokenize", "strtonum", "recordio", "input_split",
              "parquet", "pyarrow"),
    "assemble": ("native:assemble", "native:gang_assemble", "padding",
                 "assemble", "stack_padded", "pad_to_bucket",
                 "pad_single"),
    "xfer": ("device", "xfer", "transfer", "staging", "backends"),
    "wire": ("objstore", "urlopen", "http", "emulator", "pagestore"),
}


def _hot_frames_for(bound: str,
                    profile_doc: Optional[Dict[str, Any]] = None,
                    limit: int = 8
                    ) -> Tuple[List[Dict[str, Any]], str]:
    """Top on-CPU frames of the bound component, from an explicit
    profile ``to_dict()`` payload or the process's installed sampling
    profiler. Returns ``(frames, scope)`` — scope "bound" when the
    frames actually matched the bound's hints, "overall" when the
    bound HAS no frame vocabulary (consumer/credit-limited), and
    "fallback" when hints existed but nothing matched (the evidence
    line must SAY which, or the fallback fabricates the very stage
    attribution it exists to avoid). ``([], "bound")`` when nothing
    was sampled at all."""
    if profile_doc is None:
        try:
            from dmlc_tpu.obs import profile as _prof
            p = _prof.active()
            profile_doc = p.to_dict() if p is not None else None
        except Exception:  # noqa: BLE001 — evidence is optional
            profile_doc = None
    if not profile_doc or not profile_doc.get("samples"):
        return [], "bound"
    from dmlc_tpu.obs.profile import hot_frames
    hints = _BOUND_FRAME_HINTS.get(bound)
    if hints is None:
        return hot_frames(profile_doc, hints=None, limit=limit), \
            "overall"
    out = hot_frames(profile_doc, hints=hints, limit=limit)
    if out:
        return out, "bound"
    return hot_frames(profile_doc, hints=None, limit=limit), \
        "fallback"


def attribute(pipeline_snap: Dict[str, Any],
              metrics: Optional[Dict[str, Any]] = None,
              epoch_gauges: Optional[List[float]] = None,
              run_band: Optional[str] = None,
              profile_doc: Optional[Dict[str, Any]] = None,
              epoch: Optional[int] = None
              ) -> Dict[str, Any]:
    """Decompose one epoch into a bound verdict.

    ``pipeline_snap`` is a pipeline stats snapshot
    (``PIPELINE_STATS_SCHEMA``: ``CompiledPipeline.stats()``, the
    ``pipeline`` collector in a registry snapshot, or the ``pipeline``
    block of a BENCH JSON). ``metrics`` is an optional registry
    snapshot for the wire-side counters (pagestore/objstore hit
    rates). ``epoch_gauges``/``run_band`` carry bench.py's credit
    gauges when available — without them the credit-limited bound
    cannot be claimed and the verdict says so. ``profile_doc`` is an
    optional :mod:`dmlc_tpu.obs.profile` ``to_dict()`` payload for
    the ``hot_frames`` evidence; when omitted, the process's
    installed sampling profiler (if any) is read.

    ``epoch`` (schema 3) defaults to the snapshot's own monotonic
    epoch counter; with it the verdict carries a stable
    ``verdict_id`` (epoch + a content digest), so a control-ledger
    record can reference the EXACT verdict that moved a knob.
    """
    stages = list(pipeline_snap.get("stages") or [])
    wall = float(pipeline_snap.get("wall_s") or 0.0)
    per_stage: Dict[str, float] = {}
    parse_s = assemble_s = xfer_s = 0.0
    assembly_path = None
    decode_path = None
    decode_wait = decode_bytes = 0
    occupancies: List[Tuple[str, float]] = []
    fused_first = False
    fused_assemble = 0.0
    for i, st in enumerate(stages):
        name = str(st.get("name", "?"))
        kind = st.get("kind")
        wait = float(st.get("wait_s") or 0.0)
        per_stage[name] = round(wait, 6)
        x = st.get("extra") or {}
        if kind == "parse":
            parse_s += wait
        elif i == 0 and kind == "assemble":
            # the fused native rung (ABI-5) folds parse INTO the first
            # assemble-kind stage: its delivery wait is the parse side,
            # with THIS stage's own measured assemble seconds carved
            # out below (not the whole pipeline's — downstream staging
            # assembly belongs to other stages). Only the fused shape
            # earns the credit — a cache- or shard-first pipeline's
            # stage-0 wait is replay/shard I/O, not parsing.
            parse_s += wait
            fused_first = True
            fused_assemble = (float(x.get("assemble_s") or 0.0)
                              + float(x.get("staging_assemble_s")
                                      or 0.0))
        assemble_s += float(x.get("assemble_s") or 0.0)
        assemble_s += float(x.get("staging_assemble_s") or 0.0)
        xfer_s += float(x.get("xfer_wait_s") or 0.0)
        if x.get("assembly_path"):
            assembly_path = x["assembly_path"]
        if x.get("decode_path"):
            # which decoder served the epoch (parquet: pyarrow golden
            # vs the native page decoder) + what it measurably moved
            decode_path = x["decode_path"]
            decode_wait = wait
            decode_bytes = int(x.get("bytes_read") or st.get("bytes")
                               or 0)
        occ = st.get("queue_occupancy")
        if occ is not None:
            occupancies.append((name, float(occ)))
    if fused_first:
        parse_s = max(0.0, parse_s - fused_assemble)
    total_wait = sum(per_stage.values())

    # wire side: pagestore hit rate + objstore GET traffic (cumulative
    # process counters — a cold remote epoch shows misses and GETs).
    # objstore.bytes counts ON-WIRE bytes (compressed when the page
    # codec is on); objstore.bytes_served the decompressed payload —
    # the wire-heaviness judgment uses the SERVED side (that is what
    # the pipeline consumed), the evidence names both rates.
    ps_hit = _counter(metrics, "pagestore.hit")
    ps_miss = _counter(metrics, "pagestore.miss")
    obj_gets = _counter(metrics, "objstore.get")
    obj_bytes = _counter(metrics, "objstore.bytes")
    obj_served = _counter(metrics, "objstore.bytes_served")
    obj_payload = obj_served or obj_bytes
    peer_gets = _counter(metrics, "objstore.peer.get")
    peer_bytes = _counter(metrics, "objstore.peer.bytes")
    peer_miss = _counter(metrics, "objstore.peer.miss")
    hit_rate = (ps_hit / (ps_hit + ps_miss)
                if (ps_hit + ps_miss) else None)
    pipeline_bytes = max((int(st.get("bytes") or 0) for st in stages),
                         default=0)
    wire_heavy = (obj_gets > 0 and obj_payload >= 0.5 * pipeline_bytes
                  and (hit_rate is None or hit_rate < 0.5))

    band = run_band or _modal_band(epoch_gauges)
    evidence: List[str] = []
    waits = {"parse": parse_s, "assemble": assemble_s, "xfer": xfer_s}

    if band != "unknown":
        mean_g = (round(sum(epoch_gauges) / len(epoch_gauges), 2)
                  if epoch_gauges else None)
        evidence.append(
            f"credit band {band}"
            + (f" (mean memcpy gauge {mean_g} GB/s over "
               f"{len(epoch_gauges)} epochs)" if mean_g is not None
               else ""))
    for comp, s in sorted(waits.items(), key=lambda kv: -kv[1]):
        if s > 0:
            frac = f" = {s / wall:.0%} of wall" if wall > 0 else ""
            evidence.append(f"{comp} wait {round(s, 4)}s{frac}")
    if assembly_path:
        evidence.append(f"assembly_path={assembly_path}")
    if decode_path:
        # the DECODE-bound leg: a config-5-shaped epoch's verdict says
        # WHICH decode path was the wall and how fast it actually ran
        # (the PR 12 controller maps parse-bound onto the parse knob
        # family — shard count first — either way)
        line = f"decode path {decode_path}"
        if decode_wait > 0 and decode_bytes:
            line += (f": {decode_bytes / decode_wait / 1e9:.2f} GB/s "
                     f"({decode_bytes} bytes over "
                     f"{round(decode_wait, 4)}s decode-stage wait)")
        evidence.append(line)
    if hit_rate is not None:
        evidence.append(f"pagestore hit rate {hit_rate:.2f} "
                        f"({int(ps_hit)} hit / {int(ps_miss)} miss)")
    if obj_gets:
        line = (f"objstore: {int(obj_gets)} GETs, "
                f"{int(obj_bytes)} wire bytes vs "
                f"{pipeline_bytes} pipeline bytes")
        if obj_served > obj_bytes:
            # page codec on: the wire moved fewer bytes than it served
            line += (f" (codec: {int(obj_served)} bytes served from "
                     f"{int(obj_bytes)} on-wire, "
                     f"{obj_served / obj_bytes:.1f}x")
            if wall > 0:
                line += (f"; {obj_bytes / wall / 1e9:.3f} GB/s "
                         "compressed wire -> "
                         f"{obj_served / wall / 1e9:.3f} GB/s served")
            line += ")"
        evidence.append(line)
    if peer_gets or peer_bytes or peer_miss:
        # the gang peer tier split: bytes that arrived from peers'
        # /pages endpoints never touched the wire — the 1/N claim,
        # named as rates so a wire verdict says which tier carried it
        line = (f"peer tier: {int(peer_gets)} peer GETs, "
                f"{int(peer_bytes)} peer-served bytes, "
                f"{int(peer_miss)} degraded to the wire")
        if wall > 0 and (peer_bytes or obj_payload):
            line += (f" ({peer_bytes / wall / 1e9:.3f} GB/s "
                     "peer-served vs "
                     f"{obj_payload / wall / 1e9:.3f} GB/s "
                     "wire-served)")
        evidence.append(line)
    rpc = ((metrics or {}).get("collectors") or {}).get("rpc")
    if isinstance(rpc, dict) and rpc.get("attributed"):
        # the RPC edge table's wire-wait decomposition (obs.rpc):
        # server-reported handle time vs the network+queue residual —
        # a wire verdict names WHERE the waiting actually happened
        server = float(rpc.get("server_us") or 0.0)
        residual = float(rpc.get("residual_us") or 0.0)
        attributed = server + residual
        if attributed > 0:
            evidence.append(
                f"wire wait: {server / attributed:.0%} server handle, "
                f"{residual / attributed:.0%} network+queue residual "
                f"over {int(rpc.get('attributed', 0))} attributed "
                f"RPCs ({int(rpc.get('count', 0))} total, "
                f"{int(rpc.get('errors', 0))} errors)")
    ck_restore = _counter(metrics, "checkpoint.restore_bytes")
    if ck_restore:
        # the checkpoint fanout split: of the bytes restore()
        # materialized, how many each tier carried — peer-served pages
        # are the ~1/N-wire claim for gang restores, named as rates
        ck_local = _counter(metrics, "checkpoint.restore.local_bytes")
        ck_peer = _counter(metrics, "checkpoint.restore.peer_bytes")
        ck_wire = _counter(metrics, "checkpoint.restore.wire_bytes")
        line = (f"checkpoint restore: {int(ck_restore)} bytes "
                f"({int(ck_local)} local, {int(ck_peer)} peer-served, "
                f"{int(ck_wire)} wire)")
        if wall > 0 and (ck_peer or ck_wire):
            line += (f" — {ck_peer / wall / 1e9:.3f} GB/s peer-served "
                     f"vs {ck_wire / wall / 1e9:.3f} GB/s wire-served")
        evidence.append(line)
    resharded = _counter(metrics, "rendezvous.reshard")
    mem_joins = _counter(metrics, "rendezvous.join")
    mem_deaths = _counter(metrics, "rendezvous.death")
    if resharded or mem_joins or mem_deaths:
        # the gang changed shape DURING this epoch: wire/peer deltas
        # above include reshard traffic (new owners fast-forwarding
        # over the page store), so the verdict names the membership
        # change instead of letting it read as a wire regression
        evidence.append(
            f"membership: {int(resharded)} reshard(s) this epoch "
            f"({int(mem_joins)} join / {int(mem_deaths)} death; "
            "gang/member/* instants on the trace, roster on /gang)")
    for name, occ in occupancies:
        if occ >= 0.8:
            evidence.append(f"queue {name} {occ:.0%} full "
                            "(producer outpacing consumer)")

    # the decision ladder: climate first (a drained credit bucket
    # swamps every in-pipeline signal), then the wire, then whichever
    # measured wait dominates, with tiny-wait epochs handed to the
    # consumer
    ranked = sorted(waits.items(), key=lambda kv: -kv[1])
    top_name, top_s = ranked[0]
    second_s = ranked[1][1]
    if band == "drained":
        bound = "credit-limited"
        confidence = "high"
        evidence.insert(0, "modal gauge band is drained: wall rates "
                        "reflect the credit scheduler, not the "
                        "pipeline")
    elif wire_heavy:
        bound = "wire"
        confidence = "high" if (hit_rate or 0) < 0.2 else "medium"
    elif wall > 0 and total_wait < _CONSUMER_WAIT_FRAC * wall:
        bound = "consumer"
        confidence = "high" if total_wait < 0.05 * wall else "medium"
        evidence.insert(0, f"stage waits total {round(total_wait, 4)}s "
                        f"= {total_wait / wall:.0%} of wall "
                        f"{round(wall, 4)}s — the pipeline is not the "
                        "bottleneck")
    elif top_s <= 0:
        bound = "consumer"
        confidence = "low"
        evidence.insert(0, "no stage reported a wait; defaulting to "
                        "consumer-bound")
    else:
        bound = top_name
        if second_s <= 0 or top_s >= 2.0 * second_s:
            confidence = "high"
        elif top_s >= 1.2 * second_s:
            confidence = "medium"
        else:
            confidence = "low"
            evidence.append(
                f"close call: {ranked[0][0]} {round(top_s, 4)}s vs "
                f"{ranked[1][0]} {round(second_s, 4)}s")
    hot, hot_scope = _hot_frames_for(bound, profile_doc)
    if hot:
        label = {"bound": f"hot frames ({bound})",
                 "overall": "hot frames (overall)",
                 "fallback": f"hot frames (overall — no sampled "
                             f"frame matched the {bound} stage)"
                 }[hot_scope]
        evidence.append(
            f"{label}: "
            + ", ".join(f"{h['frame']} {h['frac']:.0%}"
                        for h in hot[:3]))
    if epoch is None:
        try:
            epoch = int(pipeline_snap.get("epoch") or 0)
        except (TypeError, ValueError):
            epoch = 0
    stage_waits = {
        "parse_s": round(parse_s, 6),
        "assemble_s": round(assemble_s, 6),
        "xfer_s": round(xfer_s, 6),
        "total_wait_s": round(total_wait, 6),
        "wall_s": round(wall, 6),
        "stages": per_stage,
    }
    # stable id: the monotonic epoch + a digest of what was judged —
    # two verdicts over the same measurements share an id, a ledger
    # record can reference exactly the verdict that moved its knob
    tenant = pipeline_snap.get("tenant")
    digest = _hashlib.sha256(json.dumps(
        [epoch, tenant, bound, band, stage_waits],
        sort_keys=True).encode()).hexdigest()[:10]
    return {
        "schema": ANALYSIS_SCHEMA,
        "epoch": epoch,
        "verdict_id": f"v{epoch}-{digest}",
        "tenant": tenant,
        "bound": bound,
        "band": band,
        "confidence": confidence,
        "evidence": evidence,
        "hot_frames": hot,
        "stage_waits": stage_waits,
    }


def slo_verdict(name: str, row: Dict[str, Any],
                epoch: Optional[int] = None) -> Dict[str, Any]:
    """A burning SLO as a verdict: called by ``SloEngine.verdicts()``
    for each objective whose fast/slow burn alert fires, so budget
    burn rides the same ``/analyze`` → Controller → ledger path as
    stage attribution (this PR ships the verdict; knob moves on it are
    a later PR's). ``row`` is one objective row from
    ``SloEngine.view()``; bound is always ``slo``, band names WHICH
    alert (``fast-burn`` / ``slow-burn``)."""
    alerts = row.get("alerts") or {}
    band = "fast-burn" if alerts.get("fast") else "slow-burn"
    windows = row.get("windows") or {}
    long_total = int((windows.get("long") or {}).get("total") or 0)
    # confidence scales with how many observations back the judgment
    confidence = ("high" if long_total >= 100
                  else "medium" if long_total >= 10 else "low")
    evidence = [
        f"objective {name}: {row.get('metric')} <= "
        f"{row.get('target_s')}s over {row.get('window_s')}s, "
        f"budget {row.get('budget')}",
        f"budget_remaining {row.get('budget_remaining')} "
        f"(attainment {row.get('attainment')})",
    ]
    for label in ("long", "short", "fast_long", "fast_short"):
        w = windows.get(label) or {}
        evidence.append(
            f"{label} {w.get('window_s')}s: burn {w.get('burn')} "
            f"({w.get('good')}/{w.get('total')} good)")
    epoch = int(epoch or 0)
    tenant = row.get("tenant") or name
    digest = _hashlib.sha256(json.dumps(
        [epoch, tenant, "slo", band, evidence],
        sort_keys=True).encode()).hexdigest()[:10]
    return {
        "schema": ANALYSIS_SCHEMA,
        "epoch": epoch,
        "verdict_id": f"v{epoch}-{digest}",
        "tenant": tenant,
        "bound": "slo",
        "band": band,
        "confidence": confidence,
        "evidence": evidence,
        "hot_frames": [],
        "stage_waits": {},
    }


# ------------------------------------------------------- BENCH compare

def load_bench(path_or_doc) -> Dict[str, Any]:
    """Load a BENCH JSON: either the raw one-line dict bench.py
    prints, or the campaign wrapper the BENCH_r0*.json archive uses
    (``{"n", "cmd", "rc", "tail", "parsed"}`` — ``parsed`` is the
    bench line; older wrappers may only carry it inside ``tail``)."""
    if isinstance(path_or_doc, dict):
        doc = path_or_doc
    else:
        with open(path_or_doc) as f:
            doc = json.load(f)
    # bench_suite config lines (config 14 recio_native etc.) carry
    # "config" + "gbps": comparable band-for-band via their
    # epoch_gauges/gbps fallback in _bands_of
    if "metric" in doc or "pipeline" in doc or "config" in doc:
        return doc
    if isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    for line in reversed((doc.get("tail") or "").splitlines()):
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                inner = json.loads(line)
            except ValueError:
                continue
            if "metric" in inner:
                return inner
    raise ValueError("not a BENCH JSON (no metric/parsed/tail line)")


def _bands_of(doc: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Per-band sustained rates of one BENCH doc. Prefers the
    ``gauge_bands`` block (present since r6); older docs fall back to
    their modal band (from ``epoch_gauges``) carrying
    ``sustained_gauge_ok``/``value`` — and a doc with no gauges at all
    lands in band "unknown", comparable only with another unknown."""
    out: Dict[str, Dict[str, Any]] = {}
    gb = doc.get("gauge_bands")
    if isinstance(gb, dict):
        for band, v in gb.items():
            if isinstance(v, dict) and v.get("sustained") is not None:
                out[band] = {"sustained": v["sustained"],
                             "epochs": v.get("epochs")}
    if out:
        return out
    band = doc.get("run_band") or _modal_band(doc.get("epoch_gauges"))
    value = doc.get("sustained_gauge_ok")
    if value is None:
        value = doc.get("value")
    if value is None:
        value = doc.get("gbps")  # bench_suite config lines
    if value is not None:
        out[band] = {"sustained": value, "epochs": doc.get("epochs")}
    return out


def compare(doc_a: Dict[str, Any], doc_b: Dict[str, Any],
            tolerance: float = DEFAULT_TOLERANCE) -> Dict[str, Any]:
    """Band-aware diff of two BENCH docs (a = baseline, b = candidate).

    Rates are compared WITHIN one credit band only; a band present in
    just one run is reported ``incomparable`` (the climate differed,
    not necessarily the code). ``parse_cpu_gbps_core`` — the
    credit-immune kernel rate — is compared across the whole run
    regardless of band. Deltas within ±``tolerance`` are ``in-band``
    variance, never regressions."""
    a, b = load_bench(doc_a), load_bench(doc_b)
    bands_a, bands_b = _bands_of(a), _bands_of(b)
    rows: Dict[str, Dict[str, Any]] = {}
    regressions: List[str] = []
    improvements: List[str] = []
    for band in sorted(set(bands_a) | set(bands_b)):
        ra, rb = bands_a.get(band), bands_b.get(band)
        if ra is None or rb is None:
            rows[band] = {"a": ra and ra["sustained"],
                          "b": rb and rb["sustained"],
                          "epochs": [ra and ra.get("epochs"),
                                     rb and rb.get("epochs")],
                          "delta_frac": None,
                          "status": "incomparable"}
            continue
        va, vb = float(ra["sustained"]), float(rb["sustained"])
        delta = (vb - va) / va if va else None
        if delta is None:
            status = "incomparable"
        elif delta < -tolerance:
            status = "regression"
            regressions.append(
                f"band {band}: {va} -> {vb} GB/s ({delta:+.1%})")
        elif delta > tolerance:
            status = "improvement"
            improvements.append(
                f"band {band}: {va} -> {vb} GB/s ({delta:+.1%})")
        else:
            status = "in-band"
        rows[band] = {"a": va, "b": vb,
                      "epochs": [ra.get("epochs"), rb.get("epochs")],
                      "delta_frac": (round(delta, 4)
                                     if delta is not None else None),
                      "status": status}
    cpu = None
    ca, cb = a.get("parse_cpu_gbps_core"), b.get("parse_cpu_gbps_core")
    if ca and cb:
        delta = (cb - ca) / ca
        status = ("regression" if delta < -tolerance else
                  "improvement" if delta > tolerance else "in-band")
        if status == "regression":
            regressions.append(
                f"parse_cpu_gbps_core (credit-immune): {ca} -> {cb} "
                f"({delta:+.1%})")
        elif status == "improvement":
            improvements.append(
                f"parse_cpu_gbps_core (credit-immune): {ca} -> {cb} "
                f"({delta:+.1%})")
        cpu = {"a": ca, "b": cb, "delta_frac": round(delta, 4),
               "status": status}
    return {
        "schema": ANALYSIS_SCHEMA,
        "tolerance": tolerance,
        "a": {"value": a.get("value"), "run_band": a.get("run_band"),
              "bound": a.get("bound"), "epochs": a.get("epochs")},
        "b": {"value": b.get("value"), "run_band": b.get("run_band"),
              "bound": b.get("bound"), "epochs": b.get("epochs")},
        "bands": rows,
        "parse_cpu": cpu,
        "regressions": regressions,
        "improvements": improvements,
    }


def compare_files(path_a: str, path_b: str,
                  tolerance: float = DEFAULT_TOLERANCE
                  ) -> Dict[str, Any]:
    return compare(load_bench(path_a), load_bench(path_b),
                   tolerance=tolerance)


def diagnose_bench(path_or_doc) -> Dict[str, Any]:
    """Attribute a finished BENCH run offline from its embedded
    telemetry (pipeline stage snapshot + registry snapshot + epoch
    gauges) — obsctl's ``diagnose BENCH.json`` path. Prefers the
    run's own embedded ``analysis`` when present (re-deriving would
    hide what the run itself claimed)."""
    doc = load_bench(path_or_doc)
    if isinstance(doc.get("analysis"), dict):
        return doc["analysis"]
    pipeline = doc.get("pipeline") or {}
    snap = {"stages": pipeline.get("stages") or [], "wall_s": None}
    # the BENCH doc carries no wall_s at top level; derive it from the
    # best epoch's rate when possible
    if doc.get("best_epoch") and doc.get("metric"):
        stages = snap["stages"]
        nbytes = max((int(s.get("bytes") or 0) for s in stages),
                     default=0)
        if nbytes:
            snap["wall_s"] = nbytes / (float(doc["best_epoch"]) * 1e9)
    return attribute(snap, metrics=doc.get("metrics"),
                     epoch_gauges=doc.get("epoch_gauges"),
                     run_band=doc.get("run_band"))
