"""Continuous sampling profiler: merged Python+native flamegraphs.

The plane built through PR 8 stops at STAGE granularity: ``/analyze``
can say an epoch is parse-bound, the stage probes can say where the
waits sat — but nothing in the system can say which FUNCTION inside
parse is hot. This module is that last rung: an always-on-capable
wall-clock sampler on the same install/env/budget contracts as
:mod:`dmlc_tpu.obs.timeseries`.

A stdlib-only daemon thread walks :func:`sys._current_frames` at
``DMLC_TPU_PROFILE_HZ`` (set per worker by
``launch_local(profile_hz=...)``) and folds every thread's stack into
a :class:`FrameTrie` — a weighted prefix tree under a fixed byte
budget that COARSENS when full instead of truncating (the
TimeSeriesRing discipline): the lightest leaves fold their weight into
their parent's ``[coarsened]`` aggregate, so total sample weight is
conserved while the coldest call paths lose resolution first. The
sampler itself runs under a DUTY-CYCLE guard: its thread-CPU cost is
measured over 32-tick windows, and when walking the process would
exceed ``MAX_DUTY`` (~1.7% of wall — hundreds of threads, deep
stacks) the period stretches instead of the pipeline paying —
always-on means "<2% overhead", not "hz at any price". Threads
are labeled with their live :mod:`threading` names — the same
vocabulary ``TraceRecorder.name_thread`` puts on the Perfetto
timeline — and wait-shaped leaf frames (lock/queue/sleep/select) are
classified so on-CPU and off-CPU time separate under a synthetic
``[off-cpu]`` leaf.

The native half: the engine's reader/parse/assemble workers are NOT
Python threads — ``sys._current_frames`` is blind to them, which is
exactly where a fused epoch spends its time. Each engine worker keeps
a seqlock-stamped phase beacon (``{phase, shard}``; engine.cc, read
via the ``dtp_prof_*`` ctypes surface next to the busy-ns counters),
and the SAME sampler tick folds those beacons in as native leaves
(``native:parse``, ``native:reader_wait``, ``native:gang_assemble``)
under their established track names (``native/reader``,
``native/worker-N``, ``native/consumer``) — one flamegraph spanning
the GIL boundary.

Read it everywhere the plane already lives: ``GET /profile`` on the
status server (``?seconds=N&hz=M`` for an on-demand burst),
``scripts/obsctl.py profile``, collapsed-stack / speedscope exports in
:mod:`dmlc_tpu.obs.export`, a forced burst in watchdog stall reports
and flight crash bundles (``profile.txt``), and the top folded frames
of the bound stage as ``hot_frames`` evidence in the ``/analyze``
verdict.
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["FrameTrie", "StackProfiler", "active", "install",
           "uninstall", "install_if_env", "classify_wait", "hot_frames",
           "dump_collapsed", "ENV_PROFILE_HZ", "ENV_PROFILE_BYTES",
           "PROFILE_SCHEMA", "WAIT_FRAME", "FOLDED_FRAME",
           "DEFAULT_HZ", "DEFAULT_BUDGET_BYTES"]

# bump when to_dict()'s top-level shape changes incompatibly
PROFILE_SCHEMA = 1

ENV_PROFILE_HZ = "DMLC_TPU_PROFILE_HZ"        # sample rate (enables)
ENV_PROFILE_BYTES = "DMLC_TPU_PROFILE_BYTES"  # trie byte budget

DEFAULT_HZ = 67.0             # off-round: avoids lockstep with 10/100 Hz
DEFAULT_BUDGET_BYTES = 512 << 10
MAX_STACK_DEPTH = 64

# synthetic frames (never real code): the off-CPU leaf a wait-shaped
# sample lands under, the render-time leaf a node's coarsened
# (folded-away) descendants aggregate into, and the shared root that
# cold thread roots collapse into when the budget demands it
WAIT_FRAME = "[off-cpu]"
FOLDED_FRAME = "[coarsened]"
OTHER_THREADS = "[other-threads]"

# anonymous churny thread names collapse to one label: every
# ThreadingHTTPServer request handler is a fresh "Thread-N", and a
# long-profiled worker scraping /metrics would otherwise mint a new
# trie ROOT per connection — roots named by a counter carry no
# identity worth a node each
_ANON_THREAD_RE = re.compile(
    r"^(Thread|Dummy)-\d+( \(.*\))?$|"
    r"^(ThreadPoolExecutor-\d+)_\d+$")


def _normalize_label(name: str) -> str:
    m = _ANON_THREAD_RE.match(name)
    if m is None:
        return name
    return (m.group(3) + "_*") if m.group(3) else (m.group(1) + "-*")

# wait-shaped leaf sites: a thread whose INNERMOST Python frame is one
# of these is blocked, not computing. Keyed by stdlib file basename
# (time.sleep and lock.acquire are C — the blocked thread's innermost
# PYTHON frame is the stdlib wrapper, threading.py:wait etc.), plus a
# small generic set for wrappers named after what they do. A
# heuristic, and an explicitly conservative one: misclassifying a hot
# frame as a wait hides real CPU, the reverse only inflates on-CPU.
_WAIT_FILE_FUNCS = {
    "threading.py": {"wait", "acquire", "join",
                     "_wait_for_tstate_lock"},
    "queue.py": {"get", "put", "join"},
    "selectors.py": {"select", "_select", "poll"},
    "socket.py": {"accept", "recv", "recv_into", "recvfrom",
                  "sendall", "connect", "readinto"},
    "socketserver.py": {"serve_forever", "get_request",
                        "handle_request"},
    "subprocess.py": {"wait", "_wait", "_try_wait", "communicate"},
    "connection.py": {"poll", "recv", "accept", "_recv"},
    "ssl.py": {"read", "recv", "do_handshake"},
    "popen_fork.py": {"poll", "wait"},
}
# bare-name waits are kept MINIMAL: a function literally named wait/
# sleep/acquire is wait-shaped by overwhelming convention, but names
# like poll()/select()/get() are common for CPU-hot user code — those
# classify only at their file-keyed stdlib sites above (misclassifying
# a hot frame as a wait hides real CPU, the harmful direction)
_WAIT_ANY_FUNCS = {"wait", "acquire", "sleep"}

# native beacon decode (engine.cc ProfPhase/ProfKind, read through
# bindings.prof_read): phase -> (leaf frame, is_wait)
_NATIVE_PHASES = {
    1: ("native:read", False),
    2: ("native:reader_wait", True),
    3: ("native:parse", False),
    4: ("native:worker_wait", True),
    5: ("native:assemble", False),
    6: ("native:gang_assemble", False),
}


def classify_wait(file_base: str, func: str) -> bool:
    """True when a (file basename, function) leaf is wait-shaped."""
    return (func in _WAIT_FILE_FUNCS.get(file_base, ())
            or func in _WAIT_ANY_FUNCS)


class _Node:
    __slots__ = ("name", "children", "self_n", "folded_n")

    def __init__(self, name: str):
        self.name = name
        self.children: Dict[str, "_Node"] = {}
        self.self_n = 0
        self.folded_n = 0


def _node_bytes(name: str) -> int:
    # stable estimate (dict slot + node + key text): the budget check
    # and the tests use the same arithmetic, like timeseries
    return 48 + len(name)


class FrameTrie:
    """Weighted prefix tree of sampled stacks under a byte budget.

    ``add(label, frames, wait)`` folds one root-first stack in under
    the thread-label root. When the estimated node bytes exceed the
    budget the trie COARSENS: leaves whose subtree weight is below the
    current fold threshold merge their weight into their parent's
    ``folded_n`` aggregate (rendered as a ``[coarsened]`` leaf) and
    the threshold doubles when a pass frees nothing — total weight is
    conserved, the coldest/deepest paths lose resolution first, and a
    10-second burst and a 2-hour soak both fit the same budget."""

    def __init__(self, budget_bytes: int = DEFAULT_BUDGET_BYTES):
        self.budget_bytes = max(16 << 10, int(budget_bytes))
        self.roots: Dict[str, _Node] = {}
        self.samples = 0
        self.wait_samples = 0
        self.coarsenings = 0
        self._bytes = 0
        self._min_fold = 2  # leaves below this weight fold first
        self._lock = threading.Lock()

    def add(self, label: str, frames: Iterable[str],
            wait: bool = False) -> None:
        with self._lock:
            self.samples += 1
            if wait:
                self.wait_samples += 1
            node = self.roots.get(label)
            if node is None:
                node = _Node(label)
                self.roots[label] = node
                self._bytes += _node_bytes(label)
            for name in frames:
                child = node.children.get(name)
                if child is None:
                    child = _Node(name)
                    node.children[name] = child
                    self._bytes += _node_bytes(name)
                node = child
            node.self_n += 1
            if self._bytes > self.budget_bytes:
                self._coarsen_locked()

    def _fold_pass(self, node: _Node, thresh: int) -> int:
        removed = 0
        for name, child in list(node.children.items()):
            removed += self._fold_pass(child, thresh)
            if not child.children and \
                    child.self_n + child.folded_n < thresh:
                node.folded_n += child.self_n + child.folded_n
                del node.children[name]
                self._bytes -= _node_bytes(name)
                removed += 1
        return removed

    def _coarsen_locked(self) -> None:
        # caller holds the lock. Passes continue until under budget;
        # a pass that frees nothing doubles the threshold (the stride
        # analogue), so termination is guaranteed: at worst only the
        # root labels remain, carrying everything as folded weight.
        while self._bytes > self.budget_bytes:
            removed = 0
            for root in self.roots.values():
                removed += self._fold_pass(root, self._min_fold)
            # roots are nodes too: a fully-folded, cold thread root
            # (label churn the normalizer didn't anticipate) collapses
            # into the shared [other-threads] sink — without this,
            # distinct labels alone could pin the trie over budget
            # forever, and then EVERY add would re-coarsen
            sink = self.roots.get(OTHER_THREADS)
            for label, root in list(self.roots.items()):
                if root is sink or root.children:
                    continue
                if root.self_n + root.folded_n < self._min_fold:
                    if sink is None:
                        sink = _Node(OTHER_THREADS)
                        self.roots[OTHER_THREADS] = sink
                        self._bytes += _node_bytes(OTHER_THREADS)
                    sink.folded_n += root.self_n + root.folded_n
                    del self.roots[label]
                    self._bytes -= _node_bytes(label)
                    removed += 1
            self.coarsenings += 1
            if removed == 0:
                self._min_fold *= 2
                if self._min_fold > max(2, self.samples) * 2:
                    break  # nothing foldable is left below the roots

    def approx_bytes(self) -> int:
        with self._lock:
            return self._bytes

    @staticmethod
    def _node_dict(node: _Node) -> Dict[str, Any]:
        return {
            "name": node.name,
            "self": node.self_n,
            "folded": node.folded_n,
            "children": sorted(
                (FrameTrie._node_dict(c)
                 for c in node.children.values()),
                key=lambda d: -(d["self"] + d["folded"])),
        }

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "samples": self.samples,
                "wait_samples": self.wait_samples,
                "budget_bytes": self.budget_bytes,
                "approx_bytes": self._bytes,
                "coarsenings": self.coarsenings,
                "min_fold": self._min_fold,
                "threads": {label: self._node_dict(root)
                            for label, root in self.roots.items()},
            }


# (name, is_wait) per code object, built once: the sampler walks the
# same code objects every tick, and the f-string + basename + wait
# classification per frame dominated tick cost. Keyed by the code
# object itself (not id() — ids recycle after GC; holding the code
# reference is bounded by the program's distinct-function count).
_code_cache: Dict[Any, Tuple[str, bool]] = {}


def _code_info(code) -> Tuple[str, bool]:
    info = _code_cache.get(code)
    if info is None:
        # bounded: a process minting code objects forever (exec/eval,
        # JIT re-tracing) must not grow the cache — and the keys keep
        # their code objects alive — so a full cache resets and
        # rebuilds from the currently-live frames
        if len(_code_cache) >= 16384:
            _code_cache.clear()
        base = os.path.basename(code.co_filename)
        info = (f"{base}:{code.co_name}",
                classify_wait(base, code.co_name))
        _code_cache[code] = info
    return info


def _walk_stack(frame, max_depth: int) -> Tuple[List[str], bool]:
    """Root-first frame names + wait classification of the leaf."""
    wait = _code_info(frame.f_code)[1]
    names: List[str] = []
    f = frame
    depth = 0
    while f is not None and depth < max_depth:
        names.append(_code_info(f.f_code)[0])
        f = f.f_back
        depth += 1
    if f is not None:
        names.append("[truncated]")  # deeper ancestry coarsened away
    names.reverse()
    return names, wait


def _native_beacons() -> List[Tuple[int, int, int, int]]:
    """[(kind, index, phase, shard)] from the engine's phase beacons —
    only when the engine library is ALREADY loaded (profiling must
    never trigger a native build/load, the obs.trace rule)."""
    try:
        from dmlc_tpu.native import bindings
        if bindings._lib is None:
            return []
        return bindings.prof_read()
    except Exception:  # noqa: BLE001 — telemetry must never raise
        return []


def _native_label(kind: int, index: int, shard: int) -> str:
    if kind == 1:
        base = "native/reader"
    elif kind == 3:
        base = "native/consumer"
    else:
        base = f"native/worker-{index}"
    return f"{base}@shard{shard}" if shard >= 0 else base


# threads currently doing PROFILER work (a /profile burst running on
# a handler thread): excluded from every tick — a 5-second burst must
# not rank profile.py:burst as the process's hottest frame
_internal_idents: Set[int] = set()

# last measured per-tick cost, carried ACROSS profiler instances: a
# fresh sampler in this same process (install/uninstall cycles, the
# flight recorder, tests) faces the same thread population, and
# starting cold would run its whole first duty window unguarded —
# measured at ~20% of a pipeline epoch on a loaded box
_tick_cost_prior_s = 0.0


class StackProfiler:
    """The continuous sampler: one daemon thread, one FrameTrie.

    ``start()``/``stop()`` run the sampler at ``hz``;
    ``sample_now()`` takes one immediate tick (rate-limited to the
    sampler period unless ``force=True`` — crash/stall dump paths
    force so the black box carries the dying state);
    ``burst(seconds, hz)`` captures synchronously into a FRESH trie
    (the ``/profile?seconds=N`` path) while the continuous trie keeps
    accumulating; ``to_dict()`` is the ``/profile`` payload."""

    # a tick must never cost more than this fraction of wall time:
    # the sampler SLOWS DOWN instead of taxing the pipeline when a
    # tick is expensive (hundreds of threads, deep stacks) — the
    # always-on contract is "<2% overhead", not "hz at any price",
    # the same discipline as the trie byte budget
    MAX_DUTY = 0.017

    def __init__(self, hz: float = DEFAULT_HZ,
                 budget_bytes: int = DEFAULT_BUDGET_BYTES,
                 max_depth: int = MAX_STACK_DEPTH):
        self.hz = min(1000.0, max(0.1, float(hz)))
        self.period_s = 1.0 / self.hz
        self.max_depth = int(max_depth)
        self.trie = FrameTrie(budget_bytes)
        self.started_s = time.time()
        self._last_tick = 0.0
        # windowed avg CPU cost of one tick, seeded from the process
        # prior so the guard engages from tick 1 of a fresh instance
        self._tick_cost_s = _tick_cost_prior_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one tick

    def _exclude(self) -> Set[int]:
        out = set(_internal_idents)
        if self._thread is not None and self._thread.ident:
            out.add(self._thread.ident)
        return out

    def _tick_into(self, trie: FrameTrie,
                   exclude: Set[int]) -> None:
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        try:
            for ident, frame in frames.items():
                if ident in exclude:
                    continue
                stack, wait = _walk_stack(frame, self.max_depth)
                if wait:
                    stack.append(WAIT_FRAME)
                label = _normalize_label(
                    names.get(ident, "thread-?"))
                trie.add(label, stack, wait=wait)
        finally:
            del frames  # the map pins every thread's locals alive
        for kind, index, phase, shard in _native_beacons():
            leaf = _NATIVE_PHASES.get(phase)
            if leaf is None:
                continue  # idle slot (phase 0) or unknown: no time bin
            trie.add(_native_label(kind, index, shard), [leaf[0]],
                     wait=leaf[1])

    def sample_now(self, force: bool = False) -> bool:
        """One immediate sampling tick into the continuous trie.
        Non-forced calls are rate-limited to half the sampler period
        (a chatty caller must not silently multiply the sample rate);
        ``force=True`` bypasses the period — dump paths use it."""
        now = time.perf_counter()
        if not force and now - self._last_tick < 0.5 * self.period_s:
            return False
        self._last_tick = now
        try:
            self._tick_into(self.trie, self._exclude())
        except Exception:  # noqa: BLE001 — telemetry must never raise
            return False
        return True

    # -- the sampler thread

    def start(self) -> "StackProfiler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="dmlc_tpu.obs.StackProfiler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def effective_period_s(self) -> float:
        """The sampler's actual period: the configured one, stretched
        when the measured per-tick cost would push the duty cycle past
        MAX_DUTY (the wait itself releases the GIL — only tick time
        taxes the pipeline)."""
        return max(self.period_s, self._tick_cost_s / self.MAX_DUTY)

    # ticks per duty-measurement window (see _run)
    _DUTY_WINDOW = 32

    def _run(self) -> None:
        # Duty accounting: the sampler thread's OWN CPU time, averaged
        # over a window of ticks. Per-tick wall time reads scheduling
        # delay as sampler cost and throttles to near-zero exactly on
        # the loaded boxes profiles matter most; per-tick CPU time is
        # blind on hosts that account CLOCK_THREAD_CPUTIME_ID in 10 ms
        # quanta (this gVisor-class box) — a quantum landing inside a
        # 150 us tick poisons the estimate and a 2 ms tick usually
        # reads 0. Aggregated over 32 ticks the quanta average out:
        # preemption excluded, quantization bounded to ~0.3 ms/tick.
        global _tick_cost_prior_s
        ticks = 0
        # first window is SHORT (8 ticks): a cold sampler on an
        # expensive process must engage the guard within ~100 ms, not
        # after half a second of unguarded walking
        window = max(1, self._DUTY_WINDOW // 4)
        cpu0 = time.thread_time()
        while not self._stop.wait(self.effective_period_s()):
            self.sample_now(force=True)
            ticks += 1
            if ticks >= window:
                cpu1 = time.thread_time()
                self._tick_cost_s = max(0.0, (cpu1 - cpu0) / ticks)
                _tick_cost_prior_s = self._tick_cost_s
                cpu0 = cpu1
                ticks = 0
                window = self._DUTY_WINDOW

    # -- reads

    def _doc(self, trie: FrameTrie, hz: float,
             duration_s: float, burst: bool) -> Dict[str, Any]:
        doc = {"schema": PROFILE_SCHEMA, "hz": hz,
               "duration_s": round(duration_s, 3), "burst": burst,
               # what the duty-cycle guard is actually running at
               "effective_hz": round(
                   1.0 / self.effective_period_s(), 2),
               "tick_cost_s": round(self._tick_cost_s, 6)}
        doc.update(trie.to_dict())
        return doc

    def to_dict(self) -> Dict[str, Any]:
        return self._doc(self.trie, self.hz,
                         time.time() - self.started_s, burst=False)

    def collapsed_lines(self) -> List[str]:
        from dmlc_tpu.obs.export import collapsed_lines
        return collapsed_lines(self.to_dict())

    def burst(self, seconds: float,
              hz: Optional[float] = None) -> Dict[str, Any]:
        """Synchronous on-demand capture into a fresh trie (at least
        one tick even at seconds=0). Runs on the CALLING thread —
        the /profile handler thread — which is excluded from its own
        samples along with the continuous sampler thread."""
        hz = self.hz if hz is None else min(1000.0, max(0.5, float(hz)))
        seconds = max(0.0, float(seconds))
        trie = FrameTrie(self.trie.budget_bytes)
        me = threading.get_ident()
        exclude = self._exclude() | {me}
        period = 1.0 / hz
        t0 = time.perf_counter()
        deadline = t0 + seconds
        _internal_idents.add(me)  # hide this burst from the
        try:                      # continuous sampler's ticks too
            while True:
                try:
                    self._tick_into(trie, exclude)
                except Exception:  # noqa: BLE001 — keep the burst alive
                    pass
                left = deadline - time.perf_counter()
                if left <= 0:
                    break
                time.sleep(min(period, left))
        finally:
            _internal_idents.discard(me)
        return self._doc(trie, hz, time.perf_counter() - t0,
                         burst=True)


def hot_frames(doc: Dict[str, Any],
               hints: Optional[Iterable[str]] = None,
               limit: int = 8) -> List[Dict[str, Any]]:
    """Top on-CPU frames of a profile ``to_dict()`` payload:
    ``[{"frame", "samples", "frac"}]`` ranked by self weight.

    Synthetic leaves (``[off-cpu]``, ``[coarsened]``) and explicit
    native wait phases never rank — hot means CPU-hot. With ``hints``
    (lowercase substrings), only frames whose own name or any ancestor
    on the path matches are counted: "the hot frames OF the parse
    stage" is a path filter, not a leaf-name filter."""
    hints = [h.lower() for h in hints] if hints else None
    agg: Dict[str, int] = {}

    def _matches(name: str) -> bool:
        low = name.lower()
        return any(h in low for h in hints)  # type: ignore[union-attr]

    def _visit(node: Dict[str, Any], path_matched: bool) -> None:
        name = node.get("name") or "?"
        matched = path_matched or (hints is None or _matches(name))
        n = int(node.get("self") or 0)
        if (n > 0 and matched and name != WAIT_FRAME
                and name != FOLDED_FRAME
                and not name.endswith("_wait")):
            agg[name] = agg.get(name, 0) + n
        for child in node.get("children") or []:
            _visit(child, matched)

    for root in (doc.get("threads") or {}).values():
        # the thread-label root is context, not a frame: it never
        # satisfies a hint on its own
        for child in root.get("children") or []:
            _visit(child, False)
        n = int(root.get("self") or 0)
        if n and hints is None:
            agg[root.get("name") or "?"] = \
                agg.get(root.get("name") or "?", 0) + n
    total = int(doc.get("samples") or 0)
    ranked = sorted(agg.items(), key=lambda kv: (-kv[1], kv[0]))
    return [{"frame": name, "samples": n,
             "frac": round(n / total, 4) if total else 0.0}
            for name, n in ranked[:max(1, int(limit))]]


# ------------------------------------------------- process-global wiring

_profiler: Optional[StackProfiler] = None


def active() -> Optional[StackProfiler]:
    return _profiler


def install(hz: float = DEFAULT_HZ,
            budget_bytes: int = DEFAULT_BUDGET_BYTES) -> StackProfiler:
    """Install + start the process profiler (idempotent: a second call
    returns the running one — the timeseries contract)."""
    global _profiler
    if _profiler is not None:
        return _profiler
    _profiler = StackProfiler(hz=hz, budget_bytes=budget_bytes).start()
    return _profiler


def uninstall() -> None:
    global _profiler
    prof, _profiler = _profiler, None
    if prof is not None:
        prof.stop()


def install_if_env() -> Optional[StackProfiler]:
    """Gang-worker hook (one line, like timeseries.install_if_env):
    start the sampler when ``DMLC_TPU_PROFILE_HZ`` is set to a
    positive rate — ``launch_local(profile_hz=...)`` sets it per
    worker — else no-op (0 explicitly disables)."""
    raw = os.environ.get(ENV_PROFILE_HZ)
    if not raw:
        return None
    try:
        hz = float(raw)
    except ValueError as e:
        from dmlc_tpu.obs.log import warn_once
        warn_once("profile-env-failed",
                  f"obs.profile: bad {ENV_PROFILE_HZ}={raw!r}: {e}",
                  all_ranks=True)
        return None
    if hz <= 0:
        return None
    # a malformed BUDGET must not drop a valid rate request on the
    # floor: warn and fall back to the default budget
    raw_b = os.environ.get(ENV_PROFILE_BYTES)
    budget = DEFAULT_BUDGET_BYTES
    if raw_b:
        try:
            budget = int(raw_b)
        except ValueError as e:
            from dmlc_tpu.obs.log import warn_once
            warn_once("profile-bytes-env-failed",
                      f"obs.profile: bad {ENV_PROFILE_BYTES}="
                      f"{raw_b!r} ({e}); using default "
                      f"{DEFAULT_BUDGET_BYTES}", all_ranks=True)
    return install(hz=hz, budget_bytes=budget)


def dump_collapsed() -> Optional[List[str]]:
    """The crash/stall attachment: force one immediate sample (the
    sampler-period bypass, like ``TimeSeriesRing.sample_now(force=
    True)``) and return the installed profiler's collapsed-stack
    lines — or None when no profiler is installed (clean processes
    and unprofiled runs attach nothing)."""
    prof = _profiler
    if prof is None:
        return None
    try:
        prof.sample_now(force=True)
        return prof.collapsed_lines()
    except Exception:  # noqa: BLE001 — diagnostics must never raise
        return None
