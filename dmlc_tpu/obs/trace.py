"""Low-overhead, thread-aware trace recorder (span ring buffer).

The repo's ONE span API. A :class:`TraceRecorder` is a bounded ring
buffer of trace events — complete spans ("X"), instants ("i"), and
counter samples ("C") — each stamped with the recording thread. The
hot-path contract is:

- **off by default, near-zero cost when off**: instrumented code reads
  the module-global ``_recorder`` once (``rec = active()``) and skips
  all timing when it is None. The pipeline's stage probes go further
  and reuse the perf_counter pair they already measure, so a span
  costs one tuple-append beyond the telemetry the probe keeps anyway;
- **ring, not list**: a capped ``deque`` — a week-long run can leave
  tracing on and keep the LAST ``capacity`` events (``dropped`` counts
  the overwritten ones);
- **wall-anchored timestamps**: ts = wall-clock at recorder start plus
  a perf_counter delta, so traces from different processes of one gang
  merge onto a single timeline (``obs.export.merge_chrome_files``).

Export to Chrome/Perfetto trace-event JSON lives in
:mod:`dmlc_tpu.obs.export`; ``trace_to(path)`` is the one-liner.

The pre-obs ``utils.profiler`` API (named-stage accumulator + jax
device-trace context) is folded in here: :class:`Profiler` keeps its
calls/seconds/bytes aggregation semantics but every ``stage()`` now
ALSO emits a span into the active recorder, so there is one span
vocabulary, not two. ``utils/profiler.py`` is a deprecation shim.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "TraceRecorder", "active", "start", "stop", "trace_to",
    "trace_if_env", "span", "instant", "counter",
    "set_fallback", "clear_fallback", "fallback",
    "CAT_RPC_CLIENT", "CAT_RPC_SERVER",
    "Profiler", "StageStats", "profiler", "jax_trace",
]

# span categories for the cross-process RPC plane (obs.rpc): a client
# span is one attempt observed from the calling side, a server span is
# the serving handler's half. Both carry the serialized trace context
# in args — obs.export matches the pair into Perfetto flow events.
CAT_RPC_CLIENT = "rpc.client"
CAT_RPC_SERVER = "rpc.server"

# event tuples: (ph, name, cat, t_s, dur_s, tid, args)
#   ph "X": t_s = span start (perf_counter), dur_s = duration
#   ph "i": instant at t_s
#   ph "C": counter sample at t_s, args = {series: number}
_Event = Tuple[str, str, str, float, float, int, Optional[dict]]


class TraceRecorder:
    """Bounded ring buffer of trace events, thread-aware."""

    def __init__(self, capacity: int = 1 << 20):
        self._events: deque = deque(maxlen=int(capacity))
        self.capacity = int(capacity)
        self.recorded = 0          # total ever recorded (>=len => drops)
        # wall anchor: ts_us(e) = (wall0 + (t - perf0)) * 1e6 — stable
        # across processes on one host, perf_counter resolution within
        self.wall0_s = time.time()
        self.perf0_s = time.perf_counter()
        self._threads: Dict[int, str] = {}
        self._lock = threading.Lock()

    # -- recording (hot path: one counted append per event; the lock
    # guards only the `recorded` read-modify-write — `+= 1` from
    # concurrent producer/consumer threads would lose increments and
    # under-report `dropped`, making a truncated trace look complete)

    def _note_thread(self) -> int:
        t = threading.current_thread()
        ident = t.ident or 0
        if ident not in self._threads:
            with self._lock:
                self._threads[ident] = t.name
        return ident

    def _count(self) -> None:
        with self._lock:
            self.recorded += 1

    def complete(self, name: str, t0_s: float, dur_s: float,
                 cat: str = "", args: Optional[dict] = None) -> None:
        """One finished span: t0_s is perf_counter() at span start."""
        self._count()
        self._events.append(
            ("X", name, cat, t0_s, dur_s, self._note_thread(), args))

    def instant(self, name: str, cat: str = "",
                args: Optional[dict] = None) -> None:
        self._count()
        self._events.append(("i", name, cat, time.perf_counter(), 0.0,
                             self._note_thread(), args))

    def counter(self, name: str, values: Dict[str, Any],
                cat: str = "") -> None:
        """One sample of a counter track (numeric series only)."""
        nums = {k: v for k, v in values.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)}
        if not nums:
            return
        self._count()
        self._events.append(("C", name, cat, time.perf_counter(), 0.0,
                             self._note_thread(), nums))

    # -- recording on behalf of NON-Python threads (the native engine's
    # span ring drains through here: events carry the engine's own small
    # thread ids, far below any pthread ident, so tracks never collide)

    def complete_at(self, name: str, t0_s: float, dur_s: float, tid: int,
                    cat: str = "", args: Optional[dict] = None) -> None:
        """One finished span attributed to an explicit thread id."""
        self._count()
        self._events.append(("X", name, cat, t0_s, dur_s, int(tid), args))

    def instant_at(self, name: str, t_s: float, tid: int, cat: str = "",
                   args: Optional[dict] = None) -> None:
        """One instant event attributed to an explicit thread id."""
        self._count()
        self._events.append(("i", name, cat, t_s, 0.0, int(tid), args))

    def name_thread(self, tid: int, name: str) -> None:
        """Register a display name for an explicit thread id (first
        registration wins, matching _note_thread's behavior)."""
        with self._lock:
            self._threads.setdefault(int(tid), name)

    # -- reading

    @property
    def dropped(self) -> int:
        return max(0, self.recorded - len(self._events))

    def events(self) -> List[_Event]:
        return list(self._events)

    def thread_names(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._threads)

    def ts_us(self, t_s: float) -> float:
        """perf_counter time → wall-anchored microseconds."""
        return (self.wall0_s + (t_s - self.perf0_s)) * 1e6


# module-global active recorder: None = tracing off. Hot paths read
# this ONCE per operation (`rec = active()`); everything else no-ops.
_recorder: Optional[TraceRecorder] = None
# the always-on FALLBACK ring (obs.flight installs its small crash
# ring here): it serves as the active recorder whenever no explicit
# trace is running, so instrumented sites still read ONE global —
# start() displaces it for the explicit trace, stop() restores it
_fallback: Optional[TraceRecorder] = None


def active() -> Optional[TraceRecorder]:
    """The installed recorder, or None when tracing is off."""
    return _recorder


def fallback() -> Optional[TraceRecorder]:
    """The installed always-on fallback ring (obs.flight), if any."""
    return _fallback


def _sync_native(on: bool) -> None:
    """Mirror the Python tracing on/off global into the native engine's
    span-ring flag — only when the engine library is ALREADY loaded
    (tracing must never trigger a native build/load)."""
    try:
        from dmlc_tpu.native import bindings
        if bindings._lib is not None:
            bindings._lib.dtp_trace_set_enabled(1 if on else 0)
    except Exception:  # noqa: BLE001 — telemetry must not raise
        pass


def set_fallback(rec: TraceRecorder) -> None:
    """Install ``rec`` as the always-on fallback ring. It becomes the
    active recorder immediately unless an explicit trace is running
    (that trace keeps recording; ``rec`` takes over at its stop())."""
    global _recorder, _fallback
    if _recorder is None or _recorder is _fallback:
        _recorder = rec
    _fallback = rec
    _sync_native(_recorder is not None)


def clear_fallback() -> Optional[TraceRecorder]:
    """Remove the fallback ring (obs.flight uninstall); returns it."""
    global _recorder, _fallback
    rec, _fallback = _fallback, None
    if _recorder is rec:
        _recorder = None
    _sync_native(_recorder is not None)
    return rec


def start(capacity: int = 1 << 20) -> TraceRecorder:
    """Install a fresh global recorder. Replacing a live one discards
    everything it held — say so, because the outer ``trace_to`` will
    then skip its export and the silent combination reads as "the
    trace was empty" instead of "two tracers fought". (Displacing the
    always-on fallback ring is the designed interplay, not a fight:
    no warning, and stop() reinstates it.)"""
    global _recorder
    if _recorder is not None and _recorder is not _fallback:
        from dmlc_tpu.obs.log import warn_limited
        warn_limited(
            "trace-recorder-replaced",
            f"obs.trace.start(): replacing an active recorder "
            f"({len(_recorder.events())} buffered events discarded; "
            "an enclosing trace_to() will not export) — nest trace "
            "scopes, don't overlap them", min_interval_s=60.0,
            all_ranks=True)
    _recorder = TraceRecorder(capacity)
    _sync_native(True)
    return _recorder


def stop() -> Optional[TraceRecorder]:
    """Uninstall and return the active EXPLICIT recorder, reinstating
    the always-on fallback ring (if one is installed). When only the
    fallback is active it stays installed and None is returned — use
    :func:`clear_fallback` to take it down."""
    global _recorder
    rec = _recorder
    if rec is None or rec is _fallback:
        return None
    _recorder = _fallback
    _sync_native(_recorder is not None)
    return rec


@contextlib.contextmanager
def trace_to(path: str, capacity: int = 1 << 20) -> Iterator[TraceRecorder]:
    """Record for the duration of the block and export Chrome
    trace-event JSON to ``path`` on exit (even on error)."""
    from dmlc_tpu.obs.export import write_chrome
    rec = start(capacity)
    try:
        yield rec
    finally:
        if stop() is rec:
            write_chrome(rec, path)


@contextlib.contextmanager
def span(name: str, cat: str = "",
         args: Optional[dict] = None) -> Iterator[None]:
    """Record the block as one complete span (no-op when tracing is
    off — the recorder check costs one global read)."""
    rec = _recorder
    if rec is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        rec.complete(name, t0, time.perf_counter() - t0, cat, args)


def instant(name: str, cat: str = "", args: Optional[dict] = None) -> None:
    rec = _recorder
    if rec is not None:
        rec.instant(name, cat, args)


def counter(name: str, values: Dict[str, Any], cat: str = "") -> None:
    rec = _recorder
    if rec is not None:
        rec.counter(name, values, cat)


# ---------------------------------------------------------------- profiler
# The folded utils.profiler surface: same aggregation semantics, spans
# now flow through the recorder above.

@dataclass
class StageStats:
    calls: int = 0
    seconds: float = 0.0
    bytes: int = 0
    items: int = 0

    @property
    def gb_per_sec(self) -> float:
        return self.bytes / self.seconds / 1e9 if self.seconds else 0.0


class Profiler:
    """Named-stage accumulator; thread-safe. Each ``stage()`` also
    emits a span into the active TraceRecorder (cat "profiler")."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stages: Dict[str, StageStats] = {}
        self.enabled = True

    @contextlib.contextmanager
    def stage(self, name: str, nbytes: int = 0,
              items: int = 0) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        rec = _recorder
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            if rec is not None:
                rec.complete(name, t0, dt, "profiler",
                             {"bytes": nbytes, "items": items}
                             if nbytes or items else None)
            self.add(name, seconds=dt, nbytes=nbytes, items=items,
                     _calls=1)

    def add(self, name: str, seconds: float = 0.0, nbytes: int = 0,
            items: int = 0, _calls: int = 1) -> None:
        with self._lock:
            st = self._stages.setdefault(name, StageStats())
            st.calls += _calls
            st.seconds += seconds
            st.bytes += nbytes
            st.items += items

    def stats(self) -> Dict[str, StageStats]:
        with self._lock:
            return dict(self._stages)

    def reset(self) -> None:
        with self._lock:
            self._stages.clear()

    def report(self) -> str:
        lines = [f"{'stage':<24}{'calls':>8}{'sec':>10}{'GB':>10}"
                 f"{'GB/s':>10}{'items':>10}"]
        for name, st in sorted(self.stats().items()):
            lines.append(
                f"{name:<24}{st.calls:>8}{st.seconds:>10.3f}"
                f"{st.bytes / 1e9:>10.3f}{st.gb_per_sec:>10.3f}"
                f"{st.items:>10}")
        return "\n".join(lines)


profiler = Profiler()  # process-global default instance

# the profiler's named-stage aggregates join the one metrics snapshot
from dmlc_tpu.obs.metrics import REGISTRY as _REGISTRY  # noqa: E402

_REGISTRY.register("profiler", profiler, Profiler.stats)


@contextlib.contextmanager
def trace_if_env(trace_dir: Optional[str] = None) -> Iterator[None]:
    """Gang-worker tracing hook: when ``DMLC_TPU_TRACE_DIR`` is set
    (``parallel.launch.launch_local(trace_dir=...)`` sets it for every
    worker) — or a dir is passed explicitly — record for the duration
    of the block and export a rank-tagged trace file into that dir;
    otherwise a no-op. ``merge_gang_traces`` stitches the files."""
    import os
    d = trace_dir or os.environ.get("DMLC_TPU_TRACE_DIR")
    if not d:
        yield
        return
    from dmlc_tpu.obs.export import worker_rank
    rank = worker_rank()
    tag = f"rank{rank}" if rank is not None else f"pid{os.getpid()}"
    os.makedirs(d, exist_ok=True)
    with trace_to(os.path.join(d, f"trace-{tag}.json")):
        yield


@contextlib.contextmanager
def jax_trace(name: str, log_dir: Optional[str] = None) -> Iterator[None]:
    """Wrap a region in a jax.profiler trace (device timeline) when
    log_dir is given, else a named TraceAnnotation; always also feeds
    the process profiler (and through it the active recorder)."""
    import jax
    with profiler.stage(name):
        if log_dir is not None:
            with jax.profiler.trace(log_dir):
                yield
        else:
            with jax.profiler.TraceAnnotation(name):
                yield
