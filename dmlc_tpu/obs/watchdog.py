"""Stall watchdog: turn a wedged pull into a diagnosis report.

The pre-PR-1 suite once hung for 870 s with zero diagnostics — a
blocked queue pull looks exactly like a slow one from the outside.
Every instrumented wait (ThreadedIter producer/consumer blocking,
pipeline stage pulls) now registers with this module while it blocks:
:func:`begin_wait`/:func:`end_wait` cost one dict write when a
watchdog is installed and a single global read when none is.

A running :class:`Watchdog` polls the registered waits; any wait older
than ``threshold_s`` produces ONE diagnosis report per stall naming
the blocked stage(s), how long each has been blocked, the live detail
each wait carries (queue occupancy/capacity, producer counters, replay
tier), a full metrics-registry snapshot (spill state, engine stats —
whatever the process registered), the trailing ``history_s`` of
time-series samples when the shared :mod:`dmlc_tpu.obs.timeseries`
ring is installed (the decay INTO the stall, not just the frozen end
state), the sampling profiler's collapsed stacks when
:mod:`dmlc_tpu.obs.profile` is installed (a forced sample first, so
the report carries the stalling state itself), and ``faulthandler``
stacks of every thread. The report lands as JSON at ``report_path`` (plus a warning
through obs.log) and in ``self.reports`` for tests/tooling.
"""

from __future__ import annotations

import faulthandler
import itertools
import json
import os
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Watchdog", "begin_wait", "end_wait", "active",
           "current_waits", "set_escalation"]

_lock = threading.Lock()
_seq = itertools.count(1)
# key -> (name, t0_perf, detail_fn, thread_name)
_waits: Dict[int, tuple] = {}
_active: Optional["Watchdog"] = None
# stall-escalation hook (obs.flight): called with every delivered
# report, AFTER the per-watchdog on_stall callback — the flight
# recorder uses it to dump a post-mortem bundle when a run wedges
_escalation: Optional[Callable[[Dict[str, Any]], None]] = None


def active() -> Optional["Watchdog"]:
    return _active


def set_escalation(
        fn: Optional[Callable[[Dict[str, Any]], None]]) -> None:
    """Install (or clear, with None) the process-wide stall-escalation
    hook. One hook: the flight recorder owns it when installed."""
    global _escalation
    _escalation = fn


def current_waits() -> List[Dict[str, Any]]:
    """The instrumented pulls blocked RIGHT NOW (name, seconds blocked,
    thread) — the /healthz wait-state surface. Readable with or without
    a running watchdog (waits only REGISTER while one is installed, so
    without one this is empty)."""
    now = time.perf_counter()
    with _lock:
        entries = list(_waits.values())
    return [{"name": name, "blocked_s": round(now - t0, 3),
             "thread": tname}
            for name, t0, _fn, tname in entries]


def begin_wait(name: str,
               detail_fn: Optional[Callable[[], Dict[str, Any]]] = None
               ) -> Optional[int]:
    """Register a (potentially) blocking pull. Returns a token for
    :func:`end_wait`, or None (free) when no watchdog is installed."""
    if _active is None:
        return None
    key = next(_seq)
    entry = (name, time.perf_counter(), detail_fn,
             threading.current_thread().name)
    with _lock:
        _waits[key] = entry
    return key


def end_wait(key: Optional[int]) -> None:
    if key is None:
        return
    with _lock:
        _waits.pop(key, None)


def _thread_stacks() -> str:
    """All-thread stacks via faulthandler (needs a real fd)."""
    try:
        with tempfile.TemporaryFile(mode="w+") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
            f.seek(0)
            return f.read()
    except Exception as e:  # noqa: BLE001 — diagnostics must not raise
        return f"<stack dump failed: {e}>"


class Watchdog:
    """Poll instrumented waits; report any that block past the
    threshold. One report per stall instance: a wait keeps its token
    for its whole blocked life, so a reported token is remembered and
    not re-reported while it stays blocked."""

    def __init__(self, threshold_s: float = 30.0,
                 interval_s: Optional[float] = None,
                 report_path: Optional[str] = None,
                 on_stall: Optional[Callable[[Dict[str, Any]], None]]
                 = None, keep_reports: int = 8,
                 history_s: float = 120.0):
        self.threshold_s = float(threshold_s)
        # how much time-series history to attach to each report (the
        # decay INTO the stall; needs the shared obs.timeseries ring)
        self.history_s = float(history_s)
        self.interval_s = (interval_s if interval_s is not None
                           else max(0.05, min(1.0, threshold_s / 4)))
        self.report_path = report_path
        self.on_stall = on_stall
        # history retention next to report_path: report_path itself
        # always holds the LATEST report, and each report also lands
        # as a timestamped sibling — a long soak used to either
        # overwrite its history (one path) or grow without bound
        self.keep_reports = max(1, int(keep_reports))
        self.reports: List[Dict[str, Any]] = []
        self._reported: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle

    def start(self) -> "Watchdog":
        global _active
        if self._thread is not None:
            return self
        # ONE watchdog owns the shared wait registry: stopping a still-
        # running predecessor here prevents its poll thread from
        # double-reporting every stall next to ours
        prev = _active
        if prev is not None and prev is not self:
            prev.stop()
        _active = self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dmlc_tpu.obs.Watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop polling. The shared wait registry is NOT cleared:
        entries remove themselves via end_wait when their pull
        unblocks, and a pull that is STILL blocked must stay visible
        to a successor watchdog (blocked waits never re-register — a
        clear here would permanently blind the successor to exactly
        the stall it was started to catch)."""
        global _active
        if _active is self:
            _active = None
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- polling

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.check()

    def check(self) -> Optional[Dict[str, Any]]:
        """One poll: report if any registered wait exceeds the
        threshold (also callable directly from tests)."""
        now = time.perf_counter()
        with _lock:
            stalled = [(k, e) for k, e in _waits.items()
                       if now - e[1] >= self.threshold_s
                       and k not in self._reported]
        if not stalled:
            return None
        blocked = []
        for key, (name, t0, detail_fn, tname) in stalled:
            detail = None
            if detail_fn is not None:
                try:
                    detail = detail_fn()
                except Exception as e:  # noqa: BLE001
                    detail = {"error": repr(e)}
            blocked.append({"name": name,
                            "blocked_s": round(now - t0, 3),
                            "thread": tname,
                            "detail": detail})
            self._reported.add(key)
        report = self._build_report(blocked)
        self.reports.append(report)
        self._deliver(report)
        return report

    def _build_report(self, blocked: List[Dict[str, Any]]
                      ) -> Dict[str, Any]:
        from dmlc_tpu.obs.metrics import REGISTRY
        try:
            metrics = REGISTRY.snapshot()
        except Exception as e:  # noqa: BLE001
            metrics = {"error": repr(e)}
        # the trailing history_s of time-series samples: the frozen
        # end state (metrics above) shows WHERE it stalled, the decay
        # into it shows WHEN the rates started dying — empty when no
        # shared ring is installed
        history: List[Dict[str, Any]] = []
        try:
            from dmlc_tpu.obs import timeseries as _ts
            ring = _ts.active()
            if ring is not None:
                ring.sample_now(force=True)
                history = ring.last(self.history_s)
        except Exception:  # noqa: BLE001 — diagnostics must not raise
            history = []
        # the sampling profiler's collapsed stacks (forced sample, the
        # period bypass): WHERE the process is burning/blocking as it
        # stalls — None when no profiler is installed
        try:
            from dmlc_tpu.obs import profile as _prof
            prof_lines = _prof.dump_collapsed()
        except Exception:  # noqa: BLE001 — diagnostics must not raise
            prof_lines = None
        return {
            "kind": "dmlc_tpu_stall_report",
            "time": time.time(),
            "pid": os.getpid(),
            "threshold_s": self.threshold_s,
            "blocked": blocked,
            "metrics": metrics,
            "history": history,
            "history_s": self.history_s,
            "profile": prof_lines,
            "stacks": _thread_stacks(),
        }

    def _deliver(self, report: Dict[str, Any]) -> None:
        names = ", ".join(b["name"] for b in report["blocked"])
        path_note = ""
        if self.report_path:
            try:
                tmp = self.report_path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(report, f, indent=1)
                os.replace(tmp, self.report_path)
                self._write_history(report)
                path_note = f" — report: {self.report_path}"
            except Exception as e:  # noqa: BLE001
                path_note = f" — report write failed: {e}"
        from dmlc_tpu.obs.log import warn_limited
        warn_limited(
            "watchdog-stall",
            f"Watchdog: pull(s) blocked > {self.threshold_s}s: "
            f"{names}{path_note}", min_interval_s=self.interval_s,
            all_ranks=True)
        if self.on_stall is not None:
            try:
                self.on_stall(report)
            except Exception:  # noqa: BLE001 — user callback
                pass
        if _escalation is not None:
            try:
                _escalation(report)
            except Exception:  # noqa: BLE001 — escalation hook
                pass

    def _write_history(self, report: Dict[str, Any]) -> None:
        """Timestamped sibling of report_path + bounded retention:
        ``stall.json`` keeps the latest, ``stall.20260803-101502-417.json``
        (..517, ...) keep the last ``keep_reports`` stalls of a soak."""
        import glob
        root, ext = os.path.splitext(self.report_path)
        ext = ext or ".json"
        t = report.get("time", time.time())
        stamp = (time.strftime("%Y%m%d-%H%M%S", time.localtime(t))
                 + f"-{int(t * 1000) % 1000:03d}")
        hist = f"{root}.{stamp}{ext}"
        tmp = hist + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1)
        os.replace(tmp, hist)
        kept = sorted(p for p in glob.glob(f"{root}.*{ext}")
                      if p != self.report_path
                      and not p.endswith(".tmp"))
        for stale in kept[:-self.keep_reports]:
            try:
                os.remove(stale)
            except OSError:
                pass
