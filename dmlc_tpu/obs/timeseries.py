"""Time-series history: a bounded, downsampling ring of metrics samples.

Everything the obs plane served before this module answers "what is
happening RIGHT NOW" — ``/metrics`` renders one
:meth:`~dmlc_tpu.obs.metrics.MetricsRegistry.snapshot`, a stall report
freezes one moment. The analysis half (gang aggregation, bottleneck
attribution, regression judgment) needs HISTORY: how the pull waits
decayed INTO the stall, what the credit gauge did across an epoch, how
rank 3's queue depth diverged from the gang.

:class:`TimeSeriesRing` keeps periodic samples of the NUMERIC leaves of
a registry snapshot (counters, numeric gauges, histogram count/sum and
p50/p99 estimates, collector numeric leaves — strings carry no
timeline) under a fixed byte budget. When the ring fills it COARSENS
instead of truncating: every other sample is dropped across the whole
history and the keep-stride doubles, so 10 seconds and 2 hours of run
both fit the same budget — old history gets coarser, it never
disappears. The oldest sample always survives a coarsening pass, so
``samples[-1].t - samples[0].t`` spans the whole recording.

One ring per process (``install()`` / ``install_if_env()`` under
``DMLC_TPU_HISTORY_S``, set per worker by
``launch_local(history_s=...)`` like the serve/flight contracts). The
shared ring is read by:

- ``StatusServer`` ``GET /history`` (live queries),
- the crash flight recorder (``history.json`` in every bundle — the
  same samples a live query would have seen, not a private sampler),
- watchdog stall reports (the decay INTO the stall),
- :mod:`dmlc_tpu.obs.aggregate` reuses the ring mechanics per rank.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from dmlc_tpu.obs.metrics import REGISTRY, MetricsRegistry

__all__ = ["TimeSeriesRing", "numeric_leaves", "install", "uninstall",
           "active", "install_if_env", "ENV_HISTORY_S",
           "ENV_HISTORY_BYTES", "TIMESERIES_SCHEMA"]

# bump when to_dict()'s top-level shape changes incompatibly
TIMESERIES_SCHEMA = 1

ENV_HISTORY_S = "DMLC_TPU_HISTORY_S"          # sample period (enables)
ENV_HISTORY_BYTES = "DMLC_TPU_HISTORY_BYTES"  # ring byte budget

DEFAULT_PERIOD_S = 15.0
DEFAULT_BUDGET_BYTES = 256 << 10


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def numeric_leaves(snap: Dict[str, Any]) -> Dict[str, float]:
    """Flatten one registry snapshot to its numeric leaves, keyed by
    section-prefixed dotted path (``counters.rows``,
    ``gauges.queue.depth``, ``histograms.wait_s.p99``,
    ``collectors.pipeline.wall_s``). Strings/None/structures are
    dropped — a timeline of reprs is noise, and the CURRENT snapshot
    still carries them for anyone who asks."""
    out: Dict[str, float] = {}
    for name, v in (snap.get("counters") or {}).items():
        out[f"counters.{name}"] = v
    for name, v in (snap.get("gauges") or {}).items():
        if _is_num(v):
            out[f"gauges.{name}"] = v
    for name, h in (snap.get("histograms") or {}).items():
        if not isinstance(h, dict):
            continue
        for k in ("count", "sum", "p50", "p99"):
            v = h.get(k)
            if _is_num(v):
                out[f"histograms.{name}.{k}"] = v
    stack: List[tuple] = [(f"collectors.{n}", v) for n, v in
                          (snap.get("collectors") or {}).items()]
    while stack:
        prefix, v = stack.pop()
        if isinstance(v, dict):
            stack.extend((f"{prefix}.{k}", x) for k, x in v.items())
        elif isinstance(v, (list, tuple)):
            stack.extend((f"{prefix}.{i}", x) for i, x in enumerate(v))
        elif _is_num(v):
            out[prefix] = v
    return out


def _sample_bytes(leaves: Dict[str, float]) -> int:
    """Approximate in-memory/JSON cost of one sample: key text + one
    number per leaf + per-sample framing. An estimate, but a STABLE
    one — the budget check and the soak-test assertion use the same
    arithmetic."""
    return 32 + sum(len(k) + 16 for k in leaves)


class TimeSeriesRing:
    """Bounded, coarsening history of numeric metric samples.

    ``append(t, leaves)`` is the primitive (the gang aggregator feeds
    REMOTE snapshots through it); ``sample_now()`` appends the local
    registry's leaves; ``start()``/``stop()`` run a daemon sampler at
    ``period_s``. Appends honor the current keep-stride: after K
    coarsening passes only every ``2**K``-th offered sample is stored,
    which holds both memory AND per-sample cost flat on very long runs.
    """

    def __init__(self, period_s: float = DEFAULT_PERIOD_S,
                 budget_bytes: int = DEFAULT_BUDGET_BYTES,
                 registry: Optional[MetricsRegistry] = None):
        self.period_s = max(0.01, float(period_s))
        self.budget_bytes = max(4 << 10, int(budget_bytes))
        self.registry = registry if registry is not None else REGISTRY
        self._lock = threading.Lock()
        # [(wall_time, leaves, est_bytes)], oldest first
        self._samples: List[tuple] = []
        self._bytes = 0
        self._stride = 1
        self._tick = 0      # offered samples (for stride skipping)
        self._offered = 0   # total offered over the ring's life
        self._coarsenings = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- appends

    def append(self, t: float, leaves: Dict[str, float],
               force: bool = False) -> bool:
        """Offer one sample at wall time ``t``. Returns True when it
        was stored (False: skipped by the current stride).
        ``force=True`` bypasses the stride — crash/stall dumps force a
        final sample so the black box carries the actual end state
        even after the ring has coarsened to a multi-minute stride."""
        with self._lock:
            self._offered += 1
            keep = force or self._tick % self._stride == 0
            self._tick += 1
            if not keep:
                return False
            est = _sample_bytes(leaves)
            self._samples.append((t, leaves, est))
            self._bytes += est
            while self._bytes > self.budget_bytes and \
                    len(self._samples) >= 8:
                self._coarsen_locked()
            return True

    def sample_now(self, t: Optional[float] = None,
                   force: bool = False) -> bool:
        """Append the local registry's numeric leaves (the sampler
        thread's body; also callable directly from tests/tools —
        pass ``force=True`` from dump paths, see :meth:`append`)."""
        try:
            leaves = numeric_leaves(self.registry.snapshot())
        except Exception:  # noqa: BLE001 — telemetry must never raise
            return False
        return self.append(time.time() if t is None else t, leaves,
                           force=force)

    def _coarsen_locked(self) -> None:
        """Halve resolution: drop every other sample across the WHOLE
        history (even indices survive, so the oldest sample — the
        run's span anchor — is never lost) and double the keep-stride
        for future appends."""
        kept = self._samples[::2]
        self._bytes = sum(s[2] for s in kept)
        self._samples = kept
        self._stride *= 2
        self._coarsenings += 1

    # -- reads

    def samples(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"t": t, "v": leaves}
                    for t, leaves, _ in self._samples]

    def last(self, seconds: float) -> List[Dict[str, Any]]:
        """The samples from the trailing ``seconds`` of wall time."""
        cutoff = time.time() - max(0.0, float(seconds))
        with self._lock:
            return [{"t": t, "v": leaves}
                    for t, leaves, _ in self._samples if t >= cutoff]

    def approx_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def to_dict(self, last_s: Optional[float] = None) -> Dict[str, Any]:
        """The /history payload (and the flight bundle's
        ``history.json``)."""
        samples = (self.last(last_s) if last_s is not None
                   else self.samples())
        with self._lock:
            return {
                "schema": TIMESERIES_SCHEMA,
                "period_s": self.period_s,
                # effective spacing of NEW samples after coarsening
                "resolution_s": self.period_s * self._stride,
                "stride": self._stride,
                "coarsenings": self._coarsenings,
                "offered": self._offered,
                "kept": len(self._samples),
                "approx_bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
                "samples": samples,
            }

    # -- the sampler thread

    def start(self) -> "TimeSeriesRing":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="dmlc_tpu.obs.TimeSeriesRing")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def _run(self) -> None:
        # first sample immediately: a short-lived worker still leaves
        # at least one point of history in its crash bundle
        self.sample_now()
        while not self._stop.wait(self.period_s):
            self.sample_now()


_ring: Optional[TimeSeriesRing] = None


def active() -> Optional[TimeSeriesRing]:
    return _ring


def install(period_s: float = DEFAULT_PERIOD_S,
            budget_bytes: int = DEFAULT_BUDGET_BYTES,
            registry: Optional[MetricsRegistry] = None) -> TimeSeriesRing:
    """Install + start the process history ring (idempotent: a second
    call returns the running ring — the flight recorder and an explicit
    install must share ONE ring, that is the point)."""
    global _ring
    if _ring is not None:
        return _ring
    _ring = TimeSeriesRing(period_s=period_s, budget_bytes=budget_bytes,
                           registry=registry).start()
    return _ring


def uninstall() -> None:
    global _ring
    ring, _ring = _ring, None
    if ring is not None:
        ring.stop()


def install_if_env() -> Optional[TimeSeriesRing]:
    """Gang-worker hook (one line, like serve_if_env): start the
    history ring when ``DMLC_TPU_HISTORY_S`` is set —
    ``launch_local(history_s=...)`` sets it per worker — else no-op."""
    raw = os.environ.get(ENV_HISTORY_S)
    if not raw:
        return None
    try:
        period = float(raw)
        budget = int(os.environ.get(ENV_HISTORY_BYTES,
                                    str(DEFAULT_BUDGET_BYTES)))
    except ValueError as e:
        from dmlc_tpu.obs.log import warn_once
        warn_once("history-env-failed",
                  f"obs.timeseries: bad {ENV_HISTORY_S}={raw!r}: {e}",
                  all_ranks=True)
        return None
    return install(period_s=period, budget_bytes=budget)
