"""Cross-process trace-context plane: Dapper-shaped causality for
every RPC edge the repo owns, in pure stdlib.

Per-rank traces (obs.trace) already merge onto one wall-anchored gang
timeline, but the merged picture is CORRELATION only — a slow wire
wait on rank 0 sits next to a busy handler on rank 2 with nothing
tying them together. This module adds the causal thread:

- :class:`TraceContext` ``(trace_id, span_id)`` — minted at the client
  call site, serialized through ONE wire format (``trace_id-span_id``)
  into the ``X-Dmlc-Trace`` HTTP header or the ``trace`` field of a
  rendezvous line-JSON message. :func:`inject`/:func:`extract` are the
  single helper pair every edge uses; no other module may spell the
  header or the serialization (scripts/lint.py gates the literal —
  client/server header drift is the classic silent tracing outage);
- **client spans** (cat ``rpc.client``) and **server spans** (cat
  ``rpc.server``) carrying the peer identity and the context string.
  ``obs.export`` turns each matched pair into Perfetto flow events
  (``ph "s"``/``"f"`` bound by the context id), so the merged gang
  trace draws an arrow from the caller's slice to the serving rank's
  handler slice;
- **operations vs attempts**: :func:`operation` pins one ``trace_id``
  for a whole retried operation (the ``resilience.guarded`` scope)
  while every attempt inside opens its own :func:`client_span` with a
  fresh ``span_id`` — a FaultPlan-injected retry shows as N countable
  client spans sharing a trace_id, not one long blur;
- a bounded per-process **RPC edge table**: per ``(peer, verb)``
  count/errors and p50/p99 of client-observed latency, server-reported
  handle time (``X-Dmlc-Handle-Us`` echo), and their difference — the
  network+queue residual that tells "slow server" from "slow wire".
  Served as ``GET /rpc``, snapshotted into ``/metrics.json`` via a
  registry collector (so gang rollups and flight bundles carry it),
  rendered by ``obsctl rpc``.

Off cost keeps the PR 3 discipline: every entry point reads the ONE
trace-recorder global and branches; with tracing off no context is
minted, no header injected, no table row touched.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, NamedTuple, Optional

from dmlc_tpu.obs import trace as _trace
from dmlc_tpu.obs.metrics import REGISTRY

__all__ = [
    "TraceContext", "new_context", "serialize", "parse",
    "inject", "extract",
    "TRACE_HEADER", "HANDLE_HEADER", "TRACE_FIELD", "HANDLE_FIELD",
    "operation", "client_span", "active_call", "emulated_server",
    "record_server_span", "note_injected_failure",
    "RpcEdgeTable", "EDGES", "view", "membership_changed",
    "RPC_SCHEMA",
]

# bump when the /rpc (and rpc.json) shape changes incompatibly
RPC_SCHEMA = 1

# the ONE spelling of the wire carriers. Every other module imports
# these names; scripts/lint.py rejects the literals anywhere else.
TRACE_HEADER = "X-Dmlc-Trace"
HANDLE_HEADER = "X-Dmlc-Handle-Us"
TRACE_FIELD = "trace"
HANDLE_FIELD = "handle_us"


class TraceContext(NamedTuple):
    """One hop's identity: ``trace_id`` names the logical operation
    (stable across retries), ``span_id`` names this attempt."""
    trace_id: str
    span_id: str


def new_context(trace_id: Optional[str] = None) -> TraceContext:
    """Mint a context: fresh 16-hex trace_id (unless continuing an
    operation) and fresh 8-hex span_id."""
    return TraceContext(trace_id or os.urandom(8).hex(),
                        os.urandom(4).hex())


def serialize(ctx: TraceContext) -> str:
    """The single wire form: ``<trace_id>-<span_id>``."""
    return f"{ctx.trace_id}-{ctx.span_id}"


def parse(value: Any) -> Optional[TraceContext]:
    """Tolerant inverse of :func:`serialize` — anything malformed
    (wrong type, no dash, empty halves) is None, never an exception:
    a garbled header must not take down a handler."""
    if not isinstance(value, str):
        return None
    trace_id, dash, span_id = value.partition("-")
    if not dash or not trace_id or not span_id:
        return None
    return TraceContext(trace_id, span_id)


def inject(ctx: TraceContext, carrier: Dict[str, Any],
           key: str = TRACE_HEADER) -> None:
    """Write ``ctx`` into a carrier mapping — HTTP header dict by
    default, ``key=TRACE_FIELD`` for line-JSON payloads."""
    carrier[key] = serialize(ctx)


def extract(carrier: Any, key: str = TRACE_HEADER
            ) -> Optional[TraceContext]:
    """Read a context back out of a carrier (``dict``, ``Message`` —
    anything with ``.get``); None when absent or malformed."""
    try:
        return parse(carrier.get(key))
    except AttributeError:
        return None


# ------------------------------------------------------------ thread state
# One thread-local pair: the operation's pinned trace_id (shared by
# every attempt under one guarded() call) and the innermost active
# client call (how transports find the context to inject and where a
# server's handle-time echo lands).

_tls = threading.local()


class _ClientCall:
    """The live client-side half of one RPC attempt."""

    __slots__ = ("ctx", "verb", "peer", "server_us")

    def __init__(self, ctx: TraceContext, verb: str, peer: str):
        self.ctx = ctx
        self.verb = verb
        self.peer = peer
        self.server_us: Optional[float] = None

    def note_server(self, handle_us: Any) -> None:
        """Record the server-reported handle time (header/field echo);
        junk values are dropped, not raised."""
        try:
            self.server_us = float(handle_us)
        except (TypeError, ValueError):
            pass


def active_call() -> Optional[_ClientCall]:
    """The innermost open client span on this thread (transports call
    this to inject the header), or None."""
    return getattr(_tls, "call", None)


@contextlib.contextmanager
def operation(site: str, peer: Optional[str] = None
              ) -> Iterator[Optional[str]]:
    """Pin one trace_id for a whole (possibly retried) client
    operation. Wrap this OUTSIDE ``resilience.guarded`` so each
    attempt's :func:`client_span` inherits the id — retries become
    countable same-trace spans. ``peer`` (when known) labels attempts
    that die before reaching the wire (see
    :func:`note_injected_failure`). No-op (yields None) when tracing
    is off."""
    if _trace.active() is None:
        yield None
        return
    prev = getattr(_tls, "trace_id", None)
    prev_peer = getattr(_tls, "op_peer", None)
    _tls.trace_id = trace_id = os.urandom(8).hex()
    _tls.op_peer = peer
    try:
        yield trace_id
    finally:
        _tls.trace_id = prev
        _tls.op_peer = prev_peer


def note_injected_failure(site: str) -> None:
    """Resilience hook: ``policy.guarded`` calls this when an armed
    FaultPlan fires BEFORE the attempt body runs — the attempt never
    reaches its transport, so no :func:`client_span` opened. Record
    the aborted attempt as a zero-length failed client span on the
    pinned trace (plus an edge-table error), so an injected retry is
    still one countable span per attempt. No-op when tracing is off
    or no :func:`operation` is pinned."""
    rec = _trace.active()
    if rec is None:
        return
    trace_id = getattr(_tls, "trace_id", None)
    if trace_id is None:
        return
    verb = site.rsplit(".", 1)[-1]
    peer = getattr(_tls, "op_peer", None) or "injected"
    ctx = new_context(trace_id)
    rec.complete(f"rpc/{verb}", time.perf_counter(), 0.0,
                 cat=_trace.CAT_RPC_CLIENT,
                 args={TRACE_FIELD: serialize(ctx), "peer": peer,
                       "verb": verb, "ok": False, "injected": True})
    EDGES.observe(peer, verb, 0.0, None, ok=False)


@contextlib.contextmanager
def client_span(verb: str, peer: str) -> Iterator[Optional[_ClientCall]]:
    """Record the block as one client-side RPC attempt: a span (cat
    ``rpc.client``) carrying the serialized context plus an edge-table
    observation. Yields the :class:`_ClientCall` (transports read its
    ``.ctx``; the server echo lands in ``.server_us``) or None with
    tracing off — in which case nothing is minted or injected."""
    rec = _trace.active()
    if rec is None:
        yield None
        return
    ctx = new_context(getattr(_tls, "trace_id", None))
    call = _ClientCall(ctx, verb, peer)
    prev = getattr(_tls, "call", None)
    _tls.call = call
    t0 = time.perf_counter()
    ok = True
    try:
        yield call
    except BaseException:
        ok = False
        raise
    finally:
        dur_s = time.perf_counter() - t0
        _tls.call = prev
        args: Dict[str, Any] = {TRACE_FIELD: serialize(ctx),
                                "peer": peer, "verb": verb, "ok": ok}
        if call.server_us is not None:
            args["server_us"] = round(call.server_us, 1)
        rec.complete(f"rpc/{verb}", t0, dur_s,
                     cat=_trace.CAT_RPC_CLIENT, args=args)
        EDGES.observe(peer, verb, dur_s * 1e6, call.server_us, ok)


@contextlib.contextmanager
def emulated_server(verb: str, peer: str = "emulator") -> Iterator[None]:
    """The objstore emulator's server half: models the same context a
    real endpoint would echo, so a single-process bench traces exactly
    like a wire run. Records a server span bound to the in-process
    client context and reports the handle time back to it."""
    call = active_call()
    if call is None:  # tracing off, or no client span: stay silent
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dur_s = time.perf_counter() - t0
        call.note_server(dur_s * 1e6)
        record_server_span(verb, serialize(call.ctx), t0, dur_s,
                           args={"peer": peer,
                                 "handle_us": round(dur_s * 1e6, 1)})


def record_server_span(verb: str, trace: str, t0_s: float, dur_s: float,
                       args: Optional[Dict[str, Any]] = None) -> None:
    """Record one server-side handler span (cat ``rpc.server``) bound
    to an inbound context string. No-op when tracing is off."""
    rec = _trace.active()
    if rec is None:
        return
    a: Dict[str, Any] = {TRACE_FIELD: trace, "verb": verb}
    if args:
        a.update(args)
    rec.complete(f"rpc/{verb}", t0_s, dur_s,
                 cat=_trace.CAT_RPC_SERVER, args=a)


# ------------------------------------------------------------- edge table

def _pctl(sorted_us: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample list."""
    return sorted_us[min(len(sorted_us) - 1,
                         int(q * len(sorted_us)))]


class _Edge:
    __slots__ = ("count", "errors", "client_total_us", "server_total_us",
                 "residual_total_us", "attributed", "client_us",
                 "server_us", "residual_us")

    def __init__(self, samples: int):
        self.count = 0
        self.errors = 0
        self.client_total_us = 0.0
        self.server_total_us = 0.0
        self.residual_total_us = 0.0
        self.attributed = 0  # observations with a server-handle echo
        self.client_us: deque = deque(maxlen=samples)
        self.server_us: deque = deque(maxlen=samples)
        self.residual_us: deque = deque(maxlen=samples)


class RpcEdgeTable:
    """Bounded per-process ``(peer, verb)`` latency attribution.

    Client-observed latency minus the server-reported handle time is
    the network+queue residual; keeping recent samples per edge gives
    p50/p99 of all three without unbounded growth. At most
    ``max_edges`` distinct keys are tracked — overflow folds into the
    ``("other", verb)`` bucket so a port-per-rank gang cannot blow up
    the table."""

    def __init__(self, max_edges: int = 64, samples: int = 512):
        self._lock = threading.Lock()
        self._max_edges = int(max_edges)
        self._samples = int(samples)
        self._edges: Dict[tuple, _Edge] = {}

    def observe(self, peer: str, verb: str, client_us: float,
                server_us: Optional[float] = None,
                ok: bool = True) -> None:
        key = (str(peer), str(verb))
        with self._lock:
            e = self._edges.get(key)
            if e is None:
                if len(self._edges) >= self._max_edges:
                    key = ("other", str(verb))
                    e = self._edges.get(key)
                if e is None:
                    e = self._edges[key] = _Edge(self._samples)
            e.count += 1
            if not ok:
                e.errors += 1
            e.client_total_us += client_us
            e.client_us.append(client_us)
            if server_us is not None:
                residual = max(0.0, client_us - server_us)
                e.attributed += 1
                e.server_total_us += server_us
                e.residual_total_us += residual
                e.server_us.append(server_us)
                e.residual_us.append(residual)

    @staticmethod
    def _summ(samples: deque) -> Optional[Dict[str, float]]:
        s = sorted(samples)
        if not s:
            return None
        return {"p50": round(_pctl(s, 0.50), 1),
                "p99": round(_pctl(s, 0.99), 1)}

    def view(self) -> Dict[str, Any]:
        """The ``GET /rpc`` document: every edge with percentiles."""
        with self._lock:
            items = sorted(self._edges.items())
            rows = []
            for (peer, verb), e in items:
                rows.append({
                    "peer": peer, "verb": verb,
                    "count": e.count, "errors": e.errors,
                    "attributed": e.attributed,
                    "client_total_us": round(e.client_total_us, 1),
                    "server_total_us": round(e.server_total_us, 1),
                    "residual_total_us": round(e.residual_total_us, 1),
                    "client_us": self._summ(e.client_us),
                    "server_us": self._summ(e.server_us),
                    "residual_us": self._summ(e.residual_us),
                })
        return {"schema": RPC_SCHEMA, "edges": rows}

    def stats(self) -> Dict[str, Any]:
        """Compact numeric totals for the metrics collector (rides
        /metrics.json into gang rollups and analyzer evidence)."""
        with self._lock:
            edges = len(self._edges)
            count = sum(e.count for e in self._edges.values())
            errors = sum(e.errors for e in self._edges.values())
            attributed = sum(e.attributed
                             for e in self._edges.values())
            client = sum(e.client_total_us
                         for e in self._edges.values())
            server = sum(e.server_total_us
                         for e in self._edges.values())
            residual = sum(e.residual_total_us
                           for e in self._edges.values())
        return {"edges": edges, "count": count, "errors": errors,
                "attributed": attributed,
                "client_us": round(client, 1),
                "server_us": round(server, 1),
                "residual_us": round(residual, 1)}

    def retire(self, peers) -> int:
        """Drop every row for the given peers (each edge key is
        ``(peer, verb)``; all verbs go). Called when rendezvous
        membership advances past a member — a departed rank's edges
        would otherwise sit in the bounded table forever, crowding out
        live peers and haunting ``obsctl rpc`` and the ``/gang``
        rollup. Returns the number of rows dropped."""
        peers = {str(p) for p in peers}
        if not peers:
            return 0
        with self._lock:
            dead = [k for k in self._edges if k[0] in peers]
            for k in dead:
                del self._edges[k]
        return len(dead)

    def reset(self) -> None:
        with self._lock:
            self._edges.clear()


EDGES = RpcEdgeTable()  # the process-global edge table

REGISTRY.register("rpc", EDGES, RpcEdgeTable.stats)


def view() -> Dict[str, Any]:
    """The process edge table as the ``/rpc`` document."""
    return EDGES.view()


# peers seen in the last rendezvous roster — retirement only ever
# touches addresses that WERE gang members, so the rendezvous service
# endpoint, the "other" overflow bucket, and emulator rows survive
# every membership change
_roster_peers: set = set()


def membership_changed(view: Dict[str, Any]) -> int:
    """Rendezvous hook (called from ``_on_membership_change``): diff
    the new roster against the last one and retire edges for departed
    members. Counts retired rows on ``rpc.edges_retired``."""
    global _roster_peers
    live = set()
    for entry in (view.get("roster") or []):
        host = entry.get("host")
        port = entry.get("port")
        if host is not None and port is not None:
            live.add(f"{host}:{port}")
    departed = _roster_peers - live
    _roster_peers = live
    n = EDGES.retire(departed) if departed else 0
    if n:
        REGISTRY.counter("rpc.edges_retired").inc(n)
    return n
