"""dmlc_tpu.obs — unified observability: tracing, metrics, watchdog.

One place to see where time went and why a pull wedged, across the
Python and native layers (docs/observability.md):

- :mod:`~dmlc_tpu.obs.trace` — thread-aware span/instant/counter ring
  buffer, near-zero cost when off; the repo's ONE span API (the old
  ``utils.profiler`` is a shim over it);
- :mod:`~dmlc_tpu.obs.export` — Chrome/Perfetto trace-event JSON
  export + gang trace merging;
- :mod:`~dmlc_tpu.obs.metrics` — counters/gauges/histograms plus the
  registered ``stats()`` surfaces, one versioned ``snapshot()``;
- :mod:`~dmlc_tpu.obs.watchdog` — stall detection over every
  instrumented wait, with a single diagnosis report (blocked stage,
  queue state, metrics snapshot, all-thread stacks);
- :mod:`~dmlc_tpu.obs.log` — the rate-limited, gang-deduplicated
  warn channel;
- :mod:`~dmlc_tpu.obs.serve` — the LIVE plane: per-rank in-process
  HTTP status server (/metrics Prometheus exposition, /healthz,
  /stacks, on-demand /trace capture) + gang scraping;
- :mod:`~dmlc_tpu.obs.flight` — the always-on crash flight recorder
  (small trace ring + periodic metrics, post-mortem bundle on
  uncaught exception, fatal signal, or watchdog-confirmed stall);
- :mod:`~dmlc_tpu.obs.timeseries` — the ANALYSIS substrate: a
  bounded, downsampling ring of periodic metric samples shared by
  /history, stall reports, and crash bundles;
- :mod:`~dmlc_tpu.obs.aggregate` — rank-0 gang aggregation onto one
  wall-anchored timeline (per-rank series, rollups, explicit
  unreachable-rank gaps; served at /gang);
- :mod:`~dmlc_tpu.obs.analyze` — bottleneck attribution (the
  structured bound verdict bench.py embeds and /analyze serves) and
  band-aware BENCH-to-BENCH regression comparison;
- :mod:`~dmlc_tpu.obs.profile` — the continuous sampling profiler:
  merged Python+native flamegraphs (sys._current_frames + the
  engine's phase beacons) in a byte-budgeted coarsening trie, served
  at /profile, attached to stall reports and crash bundles, and the
  ``hot_frames`` evidence in the analyze verdict.
"""

from dmlc_tpu.obs.aggregate import GangAggregator
from dmlc_tpu.obs.analyze import attribute, compare, gauge_band
from dmlc_tpu.obs.export import (
    chrome_events, merge_chrome_files, write_chrome,
)
from dmlc_tpu.obs.flight import FlightRecorder
from dmlc_tpu.obs.profile import FrameTrie, StackProfiler
from dmlc_tpu.obs.timeseries import TimeSeriesRing
from dmlc_tpu.obs.log import warn_limited, warn_once
from dmlc_tpu.obs.metrics import (
    METRICS_SCHEMA, REGISTRY, Counter, Gauge, Histogram, MetricsRegistry,
    merge_snapshots,
)
from dmlc_tpu.obs.serve import (
    StatusServer, render_prometheus, scrape_gang, serve,
)
from dmlc_tpu.obs.trace import (
    Profiler, StageStats, TraceRecorder, counter, instant, jax_trace,
    profiler, span, start, stop, trace_to,
)
from dmlc_tpu.obs.watchdog import Watchdog

__all__ = [
    "TraceRecorder", "span", "instant", "counter", "start", "stop",
    "trace_to", "Profiler", "StageStats", "profiler", "jax_trace",
    "chrome_events", "write_chrome", "merge_chrome_files",
    "MetricsRegistry", "REGISTRY", "Counter", "Gauge", "Histogram",
    "merge_snapshots", "METRICS_SCHEMA",
    "Watchdog", "warn_once", "warn_limited",
    "StatusServer", "serve", "render_prometheus", "scrape_gang",
    "FlightRecorder",
    "TimeSeriesRing", "GangAggregator",
    "attribute", "compare", "gauge_band",
    "StackProfiler", "FrameTrie",
]
