"""Chrome/Perfetto trace-event JSON export + gang-trace merging.

One exporter for every :class:`~dmlc_tpu.obs.trace.TraceRecorder`:
``chrome_events()`` renders the ring buffer into trace-event dicts
(the `Trace Event Format`_ required keys — ``ph``/``ts``/``pid``/
``tid``/``name`` — are pinned by tests/test_obs.py), ``write_chrome()``
wraps them in the ``{"traceEvents": [...]}`` envelope Perfetto and
chrome://tracing both load, and ``merge_chrome_files()`` concatenates
per-worker trace files from a :mod:`dmlc_tpu.parallel.launch` gang onto
one timeline — events stay distinguishable because every process tags
its own ``pid`` (and a rank-named process_name metadata track).

The sampling profiler (:mod:`dmlc_tpu.obs.profile`) exports through
here too, from the same ``to_dict()`` payload the ``/profile``
endpoint serves: ``collapsed_lines()``/``write_collapsed()`` render
the Brendan Gregg collapsed-stack format (one ``frame;frame;... N``
line per path — what ``flamegraph.pl`` and most flame tooling eat),
``speedscope_doc()``/``write_speedscope()`` the sampled-profile JSON
`speedscope`_ loads directly.

.. _Trace Event Format: https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU
.. _speedscope: https://www.speedscope.app
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from dmlc_tpu.obs.metrics import worker_rank
from dmlc_tpu.obs.trace import (CAT_RPC_CLIENT, CAT_RPC_SERVER,
                                TraceRecorder)

__all__ = ["chrome_events", "write_chrome", "merge_chrome_files",
           "collapsed_lines", "write_collapsed", "speedscope_doc",
           "write_speedscope", "worker_rank"]


def chrome_events(rec: TraceRecorder,
                  pid: Optional[int] = None,
                  process_name: Optional[str] = None) -> List[Dict[str, Any]]:
    """Render a recorder's ring buffer as trace-event dicts.

    Spans become complete ("X") events, instants "i", counter samples
    "C" (one track per counter name, one series per dict key — the
    shape Perfetto draws as stacked counter tracks). Metadata ("M")
    events name the process (rank-tagged when launched in a gang) and
    every recording thread.

    RPC spans (cat ``rpc.client``/``rpc.server``, obs.rpc) additionally
    emit Perfetto flow events bound by their trace_id — a flow start
    ("s") inside the client slice and a binding flow finish ("f",
    ``bp: "e"``) inside the server slice — so a merged gang trace draws
    an arrow from each caller to the serving rank's handler, retries
    included (every attempt shares the operation's trace_id).
    """
    from dmlc_tpu.obs.rpc import TRACE_FIELD, parse as parse_ctx
    if pid is None:
        pid = os.getpid()
    rank = worker_rank()
    if process_name is None:
        process_name = (f"dmlc_tpu rank {rank}" if rank is not None
                        else f"dmlc_tpu pid {pid}")
    out: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0, "ts": 0,
        "args": {"name": process_name},
    }]
    for ident, tname in sorted(rec.thread_names().items()):
        out.append({"ph": "M", "name": "thread_name", "pid": pid,
                    "tid": ident, "ts": 0, "args": {"name": tname}})
    for ph, name, cat, t_s, dur_s, tid, args in rec.events():
        ev: Dict[str, Any] = {
            "ph": ph, "name": name, "pid": pid, "tid": tid,
            "ts": round(rec.ts_us(t_s), 3),
        }
        if cat:
            ev["cat"] = cat
        if ph == "X":
            ev["dur"] = round(dur_s * 1e6, 3)
            if args:
                ev["args"] = args
            if cat in (CAT_RPC_CLIENT, CAT_RPC_SERVER) and args:
                ctx = parse_ctx(args.get(TRACE_FIELD))
                if ctx is not None:
                    flow: Dict[str, Any] = {
                        "name": "rpc.flow", "cat": "rpc",
                        "id": ctx.trace_id, "pid": pid, "tid": tid,
                        "ts": ev["ts"],
                    }
                    if cat == CAT_RPC_CLIENT:
                        flow["ph"] = "s"
                    else:
                        flow["ph"] = "f"
                        flow["bp"] = "e"  # bind to enclosing slice
                    out.append(ev)
                    out.append(flow)
                    continue
        elif ph == "i":
            ev["s"] = "t"  # thread-scoped instant
            if args:
                ev["args"] = args
        else:  # "C": args IS the series dict
            ev["args"] = args or {}
        out.append(ev)
    return out


def write_chrome(rec: TraceRecorder, path: str,
                 pid: Optional[int] = None,
                 process_name: Optional[str] = None) -> Dict[str, Any]:
    """Export one recorder to a Chrome trace-event JSON file. Returns
    the envelope that was written (handy for tests)."""
    doc = {
        "traceEvents": chrome_events(rec, pid=pid,
                                     process_name=process_name),
        "displayTimeUnit": "ms",
        "otherData": {
            "recorded": rec.recorded,
            "dropped": rec.dropped,
        },
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return doc


def merge_chrome_files(paths: List[str], out_path: str) -> Dict[str, Any]:
    """Concatenate per-worker trace files onto one timeline.

    Every worker exports with its own ``pid`` and a rank-tagged
    process_name track, and timestamps are wall-anchored at recording
    time (obs.trace), so merging is pure concatenation — Perfetto lays
    the gang out as one process row per rank."""
    events: List[Dict[str, Any]] = []
    meta: List[Dict[str, Any]] = []
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        events.extend(doc.get("traceEvents", []))
        meta.append({"file": os.path.basename(p),
                     **doc.get("otherData", {})})
    merged = {"traceEvents": events, "displayTimeUnit": "ms",
              "otherData": {"merged_from": meta}}
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, out_path)
    return merged


# ------------------------------------------------ profile exports

def _walk_profile(doc: Dict[str, Any]):
    """Yield (path, weight) for every weighted node of a profile
    ``to_dict()`` payload — ``path`` is root-first starting at the
    thread label; folded (coarsened-away) weight rides a synthetic
    ``[coarsened]`` leaf so no sample weight is ever dropped from an
    export."""
    from dmlc_tpu.obs.profile import FOLDED_FRAME

    def _visit(node: Dict[str, Any], path: List[str]):
        path = path + [node.get("name") or "?"]
        n = int(node.get("self") or 0)
        if n:
            yield path, n
        folded = int(node.get("folded") or 0)
        if folded:
            yield path + [FOLDED_FRAME], folded
        for child in node.get("children") or []:
            yield from _visit(child, path)

    for root in (doc.get("threads") or {}).values():
        yield from _visit(root, [])


def collapsed_lines(doc: Dict[str, Any]) -> List[str]:
    """Profile payload -> collapsed-stack lines
    (``thread;frame;frame N``), sorted for stable diffs."""
    return sorted(f"{';'.join(path)} {n}"
                  for path, n in _walk_profile(doc))


def write_collapsed(doc: Dict[str, Any], path: str) -> str:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write("\n".join(collapsed_lines(doc)) + "\n")
    os.replace(tmp, path)
    return path


def speedscope_doc(doc: Dict[str, Any],
                   name: str = "dmlc_tpu profile") -> Dict[str, Any]:
    """Profile payload -> a speedscope "sampled" profile document
    (shared frame table + per-path sample/weight arrays; the thread
    label is the root frame, so one flamegraph carries the whole
    process — Python threads and native phase tracks side by side)."""
    frames: List[str] = []
    index: Dict[str, int] = {}

    def fi(frame: str) -> int:
        i = index.get(frame)
        if i is None:
            i = index[frame] = len(frames)
            frames.append(frame)
        return i

    samples: List[List[int]] = []
    weights: List[int] = []
    for path, n in _walk_profile(doc):
        samples.append([fi(p) for p in path])
        weights.append(n)
    total = sum(weights)
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "name": name,
        "activeProfileIndex": 0,
        "exporter": "dmlc_tpu.obs",
        "shared": {"frames": [{"name": f} for f in frames]},
        "profiles": [{
            "type": "sampled",
            "name": name,
            "unit": "none",
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        }],
    }


def write_speedscope(doc: Dict[str, Any], path: str,
                     name: str = "dmlc_tpu profile") -> str:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(speedscope_doc(doc, name=name), f)
    os.replace(tmp, path)
    return path
