"""Chrome/Perfetto trace-event JSON export + gang-trace merging.

One exporter for every :class:`~dmlc_tpu.obs.trace.TraceRecorder`:
``chrome_events()`` renders the ring buffer into trace-event dicts
(the `Trace Event Format`_ required keys — ``ph``/``ts``/``pid``/
``tid``/``name`` — are pinned by tests/test_obs.py), ``write_chrome()``
wraps them in the ``{"traceEvents": [...]}`` envelope Perfetto and
chrome://tracing both load, and ``merge_chrome_files()`` concatenates
per-worker trace files from a :mod:`dmlc_tpu.parallel.launch` gang onto
one timeline — events stay distinguishable because every process tags
its own ``pid`` (and a rank-named process_name metadata track).

.. _Trace Event Format: https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from dmlc_tpu.obs.metrics import worker_rank
from dmlc_tpu.obs.trace import TraceRecorder

__all__ = ["chrome_events", "write_chrome", "merge_chrome_files",
           "worker_rank"]


def chrome_events(rec: TraceRecorder,
                  pid: Optional[int] = None,
                  process_name: Optional[str] = None) -> List[Dict[str, Any]]:
    """Render a recorder's ring buffer as trace-event dicts.

    Spans become complete ("X") events, instants "i", counter samples
    "C" (one track per counter name, one series per dict key — the
    shape Perfetto draws as stacked counter tracks). Metadata ("M")
    events name the process (rank-tagged when launched in a gang) and
    every recording thread.
    """
    if pid is None:
        pid = os.getpid()
    rank = worker_rank()
    if process_name is None:
        process_name = (f"dmlc_tpu rank {rank}" if rank is not None
                        else f"dmlc_tpu pid {pid}")
    out: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0, "ts": 0,
        "args": {"name": process_name},
    }]
    for ident, tname in sorted(rec.thread_names().items()):
        out.append({"ph": "M", "name": "thread_name", "pid": pid,
                    "tid": ident, "ts": 0, "args": {"name": tname}})
    for ph, name, cat, t_s, dur_s, tid, args in rec.events():
        ev: Dict[str, Any] = {
            "ph": ph, "name": name, "pid": pid, "tid": tid,
            "ts": round(rec.ts_us(t_s), 3),
        }
        if cat:
            ev["cat"] = cat
        if ph == "X":
            ev["dur"] = round(dur_s * 1e6, 3)
            if args:
                ev["args"] = args
        elif ph == "i":
            ev["s"] = "t"  # thread-scoped instant
            if args:
                ev["args"] = args
        else:  # "C": args IS the series dict
            ev["args"] = args or {}
        out.append(ev)
    return out


def write_chrome(rec: TraceRecorder, path: str,
                 pid: Optional[int] = None,
                 process_name: Optional[str] = None) -> Dict[str, Any]:
    """Export one recorder to a Chrome trace-event JSON file. Returns
    the envelope that was written (handy for tests)."""
    doc = {
        "traceEvents": chrome_events(rec, pid=pid,
                                     process_name=process_name),
        "displayTimeUnit": "ms",
        "otherData": {
            "recorded": rec.recorded,
            "dropped": rec.dropped,
        },
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return doc


def merge_chrome_files(paths: List[str], out_path: str) -> Dict[str, Any]:
    """Concatenate per-worker trace files onto one timeline.

    Every worker exports with its own ``pid`` and a rank-tagged
    process_name track, and timestamps are wall-anchored at recording
    time (obs.trace), so merging is pure concatenation — Perfetto lays
    the gang out as one process row per rank."""
    events: List[Dict[str, Any]] = []
    meta: List[Dict[str, Any]] = []
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        events.extend(doc.get("traceEvents", []))
        meta.append({"file": os.path.basename(p),
                     **doc.get("otherData", {})})
    merged = {"traceEvents": events, "displayTimeUnit": "ms",
              "otherData": {"merged_from": meta}}
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, out_path)
    return merged
