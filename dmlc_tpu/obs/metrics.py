"""Process-wide metrics registry: counters, gauges, histograms, and
registered ``stats()`` collectors under ONE versioned snapshot schema.

Before obs/, ``stats()`` lived in five unrelated shapes (ThreadedIter,
the native bindings, the profiler, CompiledPipeline, BufferPool) and a
reader had to know each one. Those surfaces keep their methods — their
callers depend on the shapes — but every instance now REGISTERS into
the global :data:`REGISTRY` so one ``snapshot()`` call sees them all:

- **Counter / Gauge / Histogram** — the primitive instruments for new
  code (monotonic count, last-set value, log2-bucketed distribution);
- **collectors** — weakly-held objects with a dict-returning stats
  function, polled at snapshot time. Weak registration means an
  iterator that gets garbage-collected silently leaves the registry;
  ``destroy()``-style teardown can also unregister eagerly.

``snapshot()`` returns a plain-JSON dict with a versioned schema
(:data:`METRICS_SCHEMA`, pinned by tests/test_obs.py), pid/rank-tagged
so per-worker snapshots from a gang can be merged side-by-side with
:func:`merge_snapshots` (the metrics analogue of merged trace files).
"""

from __future__ import annotations

import itertools
import os
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "REGISTRY", "merge_snapshots", "worker_rank",
           "METRICS_SCHEMA"]

# bump when snapshot()'s top-level shape changes incompatibly
METRICS_SCHEMA = 1


def worker_rank() -> Optional[int]:
    """This process's gang rank under the parallel.launch env contract
    (DMLC_TPU_TASK_ID, reference-name alias accepted); None standalone
    or when the var is malformed. The ONE implementation — obs.export
    and obs.log read rank through here."""
    for name in ("DMLC_TPU_TASK_ID", "DMLC_TASK_ID"):
        v = os.environ.get(name)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    return None


class Counter:
    """Monotonic count (events, bytes, items)."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-set value; numeric or a small state string (e.g. the
    replay tier serving the current epoch)."""

    __slots__ = ("_value",)

    def __init__(self):
        self._value: Any = None

    def set(self, value: Any) -> None:
        self._value = value

    @property
    def value(self) -> Any:
        return self._value


class Histogram:
    """Bucketed distribution summary (count/sum/min/max + bucket counts
    keyed by upper bound). Default buckets are log2-doubling from 1e-6
    — cheap enough for per-pull waits; pass explicit ``bounds`` (sorted
    positive upper bounds, e.g. obs.slo.latency_bounds) when judgment
    accuracy at a specific value matters more than range: observations
    past the last bound land in a ``float("inf")`` overflow bucket."""

    __slots__ = ("_lock", "count", "total", "min", "max", "_buckets",
                 "_bounds", "_lower")

    def __init__(self, bounds: Optional[List[float]] = None):
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._buckets: Dict[float, int] = {}
        if bounds is not None:
            bounds = [float(b) for b in bounds]
            if (not bounds or bounds[0] <= 0
                    or any(b >= a for b, a in zip(bounds, bounds[1:]))):
                raise ValueError(
                    "Histogram bounds must be positive and strictly "
                    f"increasing, got {bounds!r}")
            self._bounds: Optional[List[float]] = bounds
            # per-bucket lower edge for quantile interpolation (log2
            # buckets derive it as ub/2; explicit bounds can't)
            self._lower: Optional[Dict[float, float]] = {
                ub: (bounds[i - 1] if i else 0.0)
                for i, ub in enumerate(bounds)}
            self._lower[float("inf")] = bounds[-1]
        else:
            self._bounds = None
            self._lower = None

    def _bucket(self, v: float) -> float:
        if self._bounds is not None:
            for ub in self._bounds:
                if v <= ub:
                    return ub
            return float("inf")
        if v <= 0:
            return 0.0
        b = 1e-6
        while b < v:
            b *= 2
        return b

    def observe(self, v: float) -> None:
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            b = self._bucket(v)
            self._buckets[b] = self._buckets.get(b, 0) + 1

    def _quantile_locked(self, q: float) -> Optional[float]:
        """Estimate the q-quantile from the log2 buckets: walk the
        cumulative counts to the target bucket, interpolate linearly
        inside it (bucket lower bound = upper/2 for log2 buckets),
        clamp to the observed min/max. Called with the lock held."""
        if not self.count:
            return None
        target = q * self.count
        cum = 0
        for ub, n in sorted(self._buckets.items()):
            prev = cum
            cum += n
            if cum >= target:
                if self._lower is not None:
                    lo = self._lower.get(ub, 0.0)
                else:
                    lo = 0.0 if ub <= 0 else ub / 2.0
                if ub == float("inf"):
                    # overflow bucket has no upper edge to interpolate
                    # toward; the observed max is the best estimate
                    est = self.max if self.max is not None else lo
                else:
                    est = lo + (ub - lo) * ((target - prev) / n)
                if self.min is not None:
                    est = max(est, self.min)
                if self.max is not None:
                    est = min(est, self.max)
                return round(est, 9)
        return self.max

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {"count": self.count, "sum": round(self.total, 9),
                    "min": self.min, "max": self.max,
                    # bucket-walk estimates (exact only at bucket
                    # edges; clamped to min/max) — the at-a-glance
                    # latency numbers /metrics renders per histogram
                    "p50": self._quantile_locked(0.5),
                    "p99": self._quantile_locked(0.99),
                    "buckets": {repr(k): v for k, v in
                                sorted(self._buckets.items())}}


def _jsonable(v: Any) -> Any:
    """Best-effort conversion of collector output to plain JSON."""
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if hasattr(v, "__dataclass_fields__"):
        return {f: _jsonable(getattr(v, f)) for f in v.__dataclass_fields__}
    if isinstance(v, (bool, str)) or v is None:
        return v
    if isinstance(v, (int, float)):
        return v
    if hasattr(v, "item"):  # numpy scalar
        try:
            return v.item()
        except Exception:  # noqa: BLE001
            return repr(v)
    return repr(v)


class MetricsRegistry:
    """get-or-create instruments + weakly-registered collectors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        # name -> (weakref to owner, fn(owner) -> dict)
        self._collectors: Dict[str, tuple] = {}
        self._seq = itertools.count(2)

    # -- instruments

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            return self._gauges.setdefault(name, Gauge())

    def histogram(self, name: str,
                  bounds: Optional[List[float]] = None) -> Histogram:
        """Get-or-create. ``bounds`` applies only when this call
        CREATES the histogram — an existing instrument keeps its
        buckets (re-bucketing live counts would corrupt them), so
        declare bounds before the first observation."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(bounds=bounds)
            return h

    def peek_histogram(self, name: str) -> Optional[Histogram]:
        """The histogram if it exists, else None — a reader (e.g. the
        SLO engine judging a declared metric) must never materialize
        an empty instrument onto /metrics."""
        with self._lock:
            return self._histograms.get(name)

    # -- collectors (the existing stats() surfaces)

    def register(self, name: str, owner: Any,
                 fn: Callable[[Any], Dict[str, Any]]) -> str:
        """Register ``fn(owner)`` as a snapshot collector. ``owner`` is
        held WEAKLY: a collected owner drops out of snapshots on its
        own. Name collisions get a ``#N`` suffix; the actual name is
        returned (pass it to :meth:`unregister`)."""
        with self._lock:
            self._prune_locked()
            actual = name
            while actual in self._collectors:
                actual = f"{name}#{next(self._seq)}"
            self._collectors[actual] = (weakref.ref(owner), fn)
            return actual

    def unregister(self, name: str) -> None:
        with self._lock:
            self._collectors.pop(name, None)

    def _prune_locked(self) -> None:
        dead = [n for n, (ref, _) in self._collectors.items()
                if ref() is None]
        for n in dead:
            del self._collectors[n]

    # -- snapshot

    def snapshot(self) -> Dict[str, Any]:
        """Freeze everything into the versioned plain-JSON shape. A
        collector that raises reports ``None`` instead of killing the
        snapshot (telemetry must never take down the pipeline)."""
        with self._lock:
            counters = {n: c.value for n, c in self._counters.items()}
            gauges = {n: _jsonable(g.value)
                      for n, g in self._gauges.items()}
            hists = {n: h.summary() for n, h in self._histograms.items()}
            collectors = dict(self._collectors)
        polled: Dict[str, Any] = {}
        for name, (ref, fn) in sorted(collectors.items()):
            owner = ref()
            if owner is None:
                continue
            try:
                polled[name] = _jsonable(fn(owner))
            except Exception:  # noqa: BLE001 — a torn-down owner
                polled[name] = None
        return {
            "schema": METRICS_SCHEMA,
            "pid": os.getpid(),
            "rank": worker_rank(),
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
            "collectors": polled,
        }

    def reset(self) -> None:
        """Drop every instrument and collector (tests)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._collectors.clear()


REGISTRY = MetricsRegistry()  # the process-global registry


def merge_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Combine per-worker snapshots into one gang view, keyed by rank
    (falling back to pid) — the report shape for multiprocess runs."""
    workers: Dict[str, Any] = {}
    for s in snaps:
        key = (f"rank{s['rank']}" if s.get("rank") is not None
               else f"pid{s.get('pid')}")
        while key in workers:
            key += "'"
        workers[key] = s
    return {"schema": METRICS_SCHEMA, "workers": workers}
