"""Always-on crash flight recorder: a black box for the data plane.

Every obs surface before this module needed either a clean exit or an
up-front decision to trace. A crash — uncaught exception, fatal
signal, a watchdog-confirmed wedge — left nothing but whatever stderr
survived. The flight recorder keeps a SMALL always-on telemetry tail
and dumps a self-contained post-mortem bundle when the process dies
badly:

- a dedicated :class:`~dmlc_tpu.obs.trace.TraceRecorder` ring
  (default 4096 events) installed as the trace module's FALLBACK
  recorder: instrumented sites still read one global, an explicit
  ``trace_to``/``start()`` displaces it for the explicit trace's
  duration and ``stop()`` reinstates it — always-on costs one branch
  plus one ring append per event, exactly the tracing-on price;
- the SHARED time-series ring (:mod:`dmlc_tpu.obs.timeseries`): the
  recorder installs the process history ring when none is running yet
  (period ``metrics_interval_s``), so the bundle's ``history.json``
  shows the minutes BEFORE the crash — the SAME samples a live
  ``GET /history`` query would have returned, not a private sampler's
  parallel universe;
- crash hooks: ``sys.excepthook`` + ``threading.excepthook`` (dump on
  uncaught exceptions), ``faulthandler`` writing fatal-signal stacks
  into the bundle dir (SIGSEGV leaves ``fatal.txt`` even though no
  Python can run), an ``atexit`` sweep that dumps if an error was seen
  but no bundle landed (and removes the empty pending dir on a clean
  exit), and the watchdog escalation hook (a confirmed stall dumps a
  bundle while the process is still alive to inspect).

Bundle layout (one timestamped dir per process under ``out_dir``)::

    flight-20260803-101502-pid4242/
      MANIFEST.json   # reason, time, pid/rank, what else is here
      trace.json      # Chrome/Perfetto export of the active ring
      metrics.json    # current snapshot + the periodic history
      history.json    # the shared time-series ring's full dump
      watchdog.json   # live blocked waits + past stall reports
      stacks.txt      # all-thread Python stacks at dump time
      env.json        # argv, python, platform, DMLC_*/JAX_* env
      error.txt       # the traceback (exception dumps)
      fatal.txt       # faulthandler output (fatal-signal deaths)
      profile.txt     # sampling profiler's collapsed stacks — forced
                      # final sample + everything accumulated (only
                      # when dmlc_tpu.obs.profile is installed)
      faults.json     # armed fault plan + injected-fault log (only
                      # when dmlc_tpu.resilience.inject chaos was on)
      control.json    # the verdict-driven controller's decision
                      # ledger + knob state (only when
                      # dmlc_tpu.obs.control is installed)
      rpc.json        # the RPC edge table: per-(peer, verb) latency
                      # attribution (only when dmlc_tpu.obs.rpc
                      # recorded at least one edge)
      slo.json        # declared SLO objectives judged at dump time:
                      # attainment, budget remaining, burn alerts
                      # (only when dmlc_tpu.obs.slo has objectives)

Wiring: ``install()`` / ``uninstall()`` directly, or
:func:`install_if_env` under ``DMLC_TPU_FLIGHT_DIR`` (set per worker
by ``launch_local(flight_dir=...)``).
"""

from __future__ import annotations

import atexit
import faulthandler
import json
import os
import sys
import tempfile
import threading
import time
import traceback
from typing import Any, Dict, Optional

from dmlc_tpu.obs import trace as _trace
from dmlc_tpu.obs import watchdog as _watchdog
from dmlc_tpu.obs.metrics import REGISTRY, worker_rank

__all__ = ["FlightRecorder", "install", "uninstall", "install_if_env",
           "active", "ENV_FLIGHT_DIR"]

ENV_FLIGHT_DIR = "DMLC_TPU_FLIGHT_DIR"


def default_flight_dir() -> str:
    return os.path.join(tempfile.gettempdir(), "dmlc_tpu_flight")


class FlightRecorder:
    """See the module docstring. One instance per process (install())."""

    def __init__(self, out_dir: Optional[str] = None,
                 ring_capacity: int = 4096,
                 metrics_interval_s: float = 15.0,
                 keep_bundles: int = 5):
        self.out_dir = out_dir or default_flight_dir()
        self.ring = _trace.TraceRecorder(ring_capacity)
        self.metrics_interval_s = float(metrics_interval_s)
        # the shared obs.timeseries ring this recorder installed (None
        # when one was already running: that one is read, not owned)
        self._owned_history = None
        self.keep_bundles = max(1, int(keep_bundles))
        stamp = time.strftime("%Y%m%d-%H%M%S")
        self.bundle_dir = os.path.join(
            self.out_dir, f"flight-{stamp}-pid{os.getpid()}")
        self.dumped = False
        self._error_seen = False
        self._lock = threading.Lock()
        self._installed = False
        self._fatal_file = None
        self._prev_excepthook = None
        self._prev_threading_hook = None

    # -- lifecycle

    def install(self) -> "FlightRecorder":
        if self._installed:
            return self
        os.makedirs(self.bundle_dir, exist_ok=True)
        self._prune_old_bundles()
        # fatal-signal stacks can only go to a pre-opened fd: no Python
        # runs during a SIGSEGV, so the bundle dir and file exist NOW
        try:
            self._fatal_file = open(
                os.path.join(self.bundle_dir, "fatal.txt"), "w")
            faulthandler.enable(file=self._fatal_file,
                                all_threads=True)
        except OSError:
            self._fatal_file = None
        _trace.set_fallback(self.ring)
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._on_exception
        self._prev_threading_hook = threading.excepthook
        threading.excepthook = self._on_thread_exception
        _watchdog.set_escalation(self._on_stall)
        atexit.register(self._at_exit)
        # the black box needs history: join the process time-series
        # ring, installing one (at this recorder's interval) only when
        # none is running — crash bundles and live /history queries
        # must read the SAME ring
        from dmlc_tpu.obs import timeseries as _ts
        if _ts.active() is None:
            self._owned_history = _ts.install(
                period_s=self.metrics_interval_s)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        from dmlc_tpu.obs import timeseries as _ts
        if (self._owned_history is not None
                and _ts.active() is self._owned_history):
            _ts.uninstall()
        self._owned_history = None
        if sys.excepthook is self._on_exception:
            sys.excepthook = self._prev_excepthook or sys.__excepthook__
        if threading.excepthook is self._on_thread_exception:
            threading.excepthook = (self._prev_threading_hook
                                    or threading.__excepthook__)
        _watchdog.set_escalation(None)
        if _trace.fallback() is self.ring:
            _trace.clear_fallback()
        try:
            atexit.unregister(self._at_exit)
        except Exception:  # noqa: BLE001
            pass
        self._close_fatal_file()
        if not self.dumped:
            self._remove_empty_bundle()

    def _close_fatal_file(self) -> None:
        if self._fatal_file is not None:
            try:
                faulthandler.disable()
                self._fatal_file.close()
            except Exception:  # noqa: BLE001
                pass
            self._fatal_file = None

    def _remove_empty_bundle(self) -> None:
        """Clean exit: a bundle holding only an empty fatal.txt is
        noise, not a post-mortem."""
        try:
            fatal = os.path.join(self.bundle_dir, "fatal.txt")
            if os.path.exists(fatal) and os.path.getsize(fatal) == 0:
                os.remove(fatal)
            if not os.listdir(self.bundle_dir):
                os.rmdir(self.bundle_dir)
        except OSError:
            pass

    def _prune_old_bundles(self) -> None:
        """Bounded retention over past runs' bundles in out_dir."""
        try:
            bundles = sorted(
                d for d in os.listdir(self.out_dir)
                if d.startswith("flight-")
                and os.path.isdir(os.path.join(self.out_dir, d)))
        except OSError:
            return
        import shutil
        for stale in bundles[:-self.keep_bundles]:
            try:
                shutil.rmtree(os.path.join(self.out_dir, stale))
            except OSError:
                pass

    # -- crash hooks

    def _on_exception(self, exc_type, exc, tb) -> None:
        self._error_seen = True
        try:
            self.dump("uncaught_exception", exc_info=(exc_type, exc, tb))
        except Exception:  # noqa: BLE001 — crashing the crash handler
            pass           # would eat the original traceback
        prev = self._prev_excepthook or sys.__excepthook__
        prev(exc_type, exc, tb)

    def _on_thread_exception(self, args) -> None:
        if args.exc_type is SystemExit:
            return
        self._error_seen = True
        try:
            self.dump("thread_exception",
                      exc_info=(args.exc_type, args.exc_value,
                                args.exc_traceback),
                      thread=getattr(args.thread, "name", None))
        except Exception:  # noqa: BLE001
            pass
        prev = self._prev_threading_hook or threading.__excepthook__
        prev(args)

    def _on_stall(self, report: Dict[str, Any]) -> None:
        """Watchdog escalation: a CONFIRMED stall dumps a bundle while
        the process is alive (the dump is refreshed per stall report —
        the last state before a kill -9 is the one that matters)."""
        self._error_seen = True
        self.dump("watchdog_stall", stall_report=report)

    def _at_exit(self) -> None:
        if self._error_seen and not self.dumped:
            try:
                self.dump("atexit_after_error")
            except Exception:  # noqa: BLE001
                pass
        self._close_fatal_file()
        if not self.dumped:
            self._remove_empty_bundle()

    # -- the dump itself

    def dump(self, reason: str, exc_info=None, thread: Optional[str] = None,
             stall_report: Optional[Dict[str, Any]] = None) -> str:
        """Write the post-mortem bundle; returns the bundle dir. Safe
        to call repeatedly (each call refreshes the same dir); every
        file is written independently so a failure in one section
        still leaves the others."""
        with self._lock:
            d = self.bundle_dir
            os.makedirs(d, exist_ok=True)
            wrote: Dict[str, str] = {}

            def _write_json(name: str, payload: Any) -> None:
                try:
                    with open(os.path.join(d, name), "w") as f:
                        json.dump(payload, f, indent=1, default=repr)
                    wrote[name] = "ok"
                except Exception as e:  # noqa: BLE001
                    wrote[name] = f"failed: {e!r}"

            # the ring that actually recorded: an explicit trace (if
            # one is running) supersedes the fallback for the bundle
            rec = _trace.active() or self.ring
            try:
                from dmlc_tpu.obs.export import chrome_events
                _write_json("trace.json", {
                    "traceEvents": chrome_events(rec),
                    "displayTimeUnit": "ms",
                    "otherData": {"recorded": rec.recorded,
                                  "dropped": rec.dropped,
                                  "flight_reason": reason},
                })
            except Exception as e:  # noqa: BLE001
                wrote["trace.json"] = f"failed: {e!r}"
            try:
                snap = REGISTRY.snapshot()
            except Exception as e:  # noqa: BLE001
                snap = {"error": repr(e)}
            # history comes from the SHARED time-series ring (one
            # last sample is forced so even a crash early in a period
            # window carries the final state)
            history = None
            try:
                from dmlc_tpu.obs import timeseries as _ts
                ring = _ts.active()
                if ring is not None:
                    ring.sample_now(force=True)
                    history = ring.to_dict()
            except Exception:  # noqa: BLE001 — optional section
                history = None
            _write_json("metrics.json", {
                "current": snap,
                "history": (history or {}).get("samples") or [],
                "interval_s": self.metrics_interval_s,
            })
            if history is not None:
                _write_json("history.json", history)
            # the sampling profiler's collapsed stacks (forced sample
            # first — the period bypass — so even a fresh profiler
            # carries the dying state): absent when none is installed,
            # so clean/unprofiled runs leave nothing extra
            try:
                from dmlc_tpu.obs import profile as _prof
                prof_lines = _prof.dump_collapsed()
            except Exception:  # noqa: BLE001 — optional section
                prof_lines = None
            if prof_lines is not None:
                try:
                    with open(os.path.join(d, "profile.txt"), "w") as f:
                        f.write("\n".join(prof_lines) + "\n")
                    wrote["profile.txt"] = "ok"
                except Exception as e:  # noqa: BLE001
                    wrote["profile.txt"] = f"failed: {e!r}"
            # the control plane's decision ledger: a post-mortem that
            # says WHICH knob moved on WHAT evidence before the death.
            # to_dict() runs user knob closures — guarded, because a
            # raising knob must cost this SECTION, never the bundle
            try:
                from dmlc_tpu.obs import control as _control
                ctl = _control.active()
                control_doc = (ctl.to_dict() if ctl is not None
                               else None)
            except Exception as e:  # noqa: BLE001 — optional section
                control_doc = None
                wrote["control.json"] = f"failed: {e!r}"
            if control_doc is not None:
                _write_json("control.json", control_doc)
            # the RPC edge table: who this process was talking to and
            # where its wire wait went, at the moment of death
            try:
                from dmlc_tpu.obs import rpc as _rpc
                rpc_doc = _rpc.view()
                if not rpc_doc.get("edges"):
                    rpc_doc = None
            except Exception as e:  # noqa: BLE001 — optional section
                rpc_doc = None
                wrote["rpc.json"] = f"failed: {e!r}"
            if rpc_doc is not None:
                _write_json("rpc.json", rpc_doc)
            # declared objectives at the moment of death: was the
            # process keeping its promises when it went down, and
            # which budget was burning
            try:
                from dmlc_tpu.obs import slo as _slo
                eng = _slo.active()
                slo_doc = (eng.view()
                           if eng is not None and eng.objectives()
                           else None)
            except Exception as e:  # noqa: BLE001 — optional section
                slo_doc = None
                wrote["slo.json"] = f"failed: {e!r}"
            if slo_doc is not None:
                _write_json("slo.json", slo_doc)
            try:
                from dmlc_tpu.resilience import inject as _inject
                plan = _inject.active()
            except Exception:  # noqa: BLE001 — optional section
                plan = None
            if plan is not None:
                # the chaos that was armed when the process died: a
                # post-mortem of an injected crash names its fault
                _write_json("faults.json", {
                    "plan": plan.spec(),
                    "seed": plan.seed,
                    "injected": plan.injected,
                    "events": plan.events(),
                })
            wd = _watchdog.active()
            _write_json("watchdog.json", {
                "installed": wd is not None,
                "threshold_s": wd.threshold_s if wd else None,
                "waits": _watchdog.current_waits(),
                "reports": list(wd.reports) if wd else [],
                "escalating_report": stall_report,
            })
            try:
                with open(os.path.join(d, "stacks.txt"), "w") as f:
                    f.write(_watchdog._thread_stacks())
                wrote["stacks.txt"] = "ok"
            except Exception as e:  # noqa: BLE001
                wrote["stacks.txt"] = f"failed: {e!r}"
            _write_json("env.json", {
                "argv": sys.argv,
                "executable": sys.executable,
                "python": sys.version,
                "platform": sys.platform,
                "cwd": os.getcwd(),
                "env": {k: v for k, v in sorted(os.environ.items())
                        if k.startswith(("DMLC_", "JAX_", "XLA_"))},
            })
            if exc_info is not None:
                try:
                    with open(os.path.join(d, "error.txt"), "w") as f:
                        if thread:
                            f.write(f"in thread {thread}:\n")
                        traceback.print_exception(*exc_info, file=f)
                    wrote["error.txt"] = "ok"
                except Exception as e:  # noqa: BLE001
                    wrote["error.txt"] = f"failed: {e!r}"
            _write_json("MANIFEST.json", {
                "kind": "dmlc_tpu_flight_bundle",
                "reason": reason,
                "time": time.time(),
                "pid": os.getpid(),
                "rank": worker_rank(),
                "files": wrote,
            })
            self.dumped = True
            return d


_flight: Optional[FlightRecorder] = None


def active() -> Optional[FlightRecorder]:
    return _flight


def install(out_dir: Optional[str] = None,
            **kwargs: Any) -> FlightRecorder:
    """Install the process flight recorder (idempotent)."""
    global _flight
    if _flight is not None:
        return _flight
    _flight = FlightRecorder(out_dir=out_dir, **kwargs).install()
    return _flight


def uninstall() -> None:
    global _flight
    fl, _flight = _flight, None
    if fl is not None:
        fl.uninstall()


def install_if_env() -> Optional[FlightRecorder]:
    """Gang-worker hook (one line, like trace_if_env): install the
    flight recorder when ``DMLC_TPU_FLIGHT_DIR`` is set —
    ``launch_local(flight_dir=...)`` sets it per worker — else no-op.
    An unwritable dir degrades to a warning, not a worker crash: the
    telemetry opt-in must never take down the job it watches."""
    d = os.environ.get(ENV_FLIGHT_DIR)
    if not d:
        return None
    try:
        return install(out_dir=d)
    except OSError as e:
        from dmlc_tpu.obs.log import warn_once
        warn_once("flight-dir-failed",
                  f"obs.flight: could not install under "
                  f"{ENV_FLIGHT_DIR}={d!r}: {e}", all_ranks=True)
        return None
