"""Declared SLOs, error budgets, and multi-rate burn alerting.

Every latency number the plane records so far is DESCRIPTIVE — a p99
with no opinion attached. ROADMAP item 2 wants the scheduler to act on
"tenant declares a p99 latency target", and acting needs a contract
first: WHO declared WHAT, how attainment is judged, and when a miss
becomes an alert. This module owns that contract:

- **objectives**: a tenant (or a pipeline, or a gang edge) registers
  ``{metric, target, window_s, budget}`` — "observations of histogram
  ``metric`` stay <= ``target`` seconds over ``window_s``, with a
  ``budget`` fraction allowed to miss" (e.g. p99 batch latency <=
  150ms over 5min, 1% error budget). Attainment is judged from the
  EXISTING histogram bucket counts (good = observations at or under
  the target, walked cumulatively), so declaring an objective adds no
  second measurement path — and with SLO-aware explicit bucket bounds
  (:func:`latency_bounds`) the target sits ON a bucket edge and the
  bucket-boundary judgment error at the target is zero;
- **sliding windows**: the engine keeps a small deque of cumulative
  ``(t, good, total)`` samples per objective and differences them, so
  window attainment needs no per-observation bookkeeping;
- **multi-rate burn alerts** (the SRE-workbook shape): burn rate =
  (1 - attainment) / budget. The FAST pair of windows (``window_s/6``
  long, ``/72`` short — the 1h/5m geometry scaled to the objective)
  fires at :data:`FAST_BURN_RATE`; the SLOW pair (``window_s`` long,
  ``/12`` short — the 6h/30m geometry) fires at
  :data:`SLOW_BURN_RATE`. An alert needs BOTH its windows over the
  rate, so a recovered tenant's short window clears the alert without
  waiting for the long window to drain. A window with no samples
  judges nothing (burn ``None``) — silence is not attainment;
- **gang rollup**: per-objective window counts ride the ``slo``
  registry collector into ``/metrics.json``, so rank 0 can
  :func:`merge_views` them and judge a gang-level objective on the
  MERGED samples; unreachable ranks mark the rollup ``incomplete``
  instead of silently skewing it (the dmlc-core tracker rule: rank 0
  owns the gang view, but never invents the missing rank).

Surfaces: ``GET /slo`` (obs.serve), ``obsctl slo``, per-objective
``slo.*`` gauges on ``/metrics`` (the lint gate confines the family —
and the burn-rate threshold literals — to this module), a merged
``slo`` section on ``/gang`` (obs.aggregate), ``slo.json`` in flight
bundles, and an ``slo``-bound verdict (:func:`analyze.slo_verdict`)
attached to ``/analyze`` while an alert fires — the PR-12 controller
can consume it in a later PR; this module ships the verdict, not the
knob moves.

Wiring mirrors the obs planes: :func:`install` / :func:`install_if_env`
under ``DMLC_TPU_SLO`` (``launch_local(slo=...)`` exports it), one
engine per process. Declarations arrive three ways: the env grammar
(``name=victim,metric=tenant.victim.batch_s,target=0.15[,window=300]
[,budget=0.01][;...]``), ``PipelineScheduler.add_tenant(slo=...)``
(which also gives the tenant's latency histogram SLO-aware bounds),
or :meth:`SloEngine.register` directly.
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

from dmlc_tpu.obs.metrics import REGISTRY as _METRICS
from dmlc_tpu.utils.logging import check

__all__ = ["SloEngine", "latency_bounds", "parse_objectives",
           "merge_views", "gang_view", "active", "install", "uninstall",
           "install_if_env", "ENV_SLO", "SLO_SCHEMA",
           "FAST_BURN_RATE", "SLOW_BURN_RATE"]

# env contract (parallel.launch.launch_local(slo=...) sets it): "1"
# installs an empty engine; otherwise parse_objectives() grammar
ENV_SLO = "DMLC_TPU_SLO"

# bump when view()'s top-level shape changes incompatibly
SLO_SCHEMA = 1

# the SRE-workbook multi-window burn-rate thresholds: fast-burn is the
# "2% of a 30d budget in 1h" rate, slow-burn the "5% in 6h" rate.
# scripts/lint.py confines these literals to THIS module — one home
# for the alert math, every surface imports the names.
FAST_BURN_RATE = 14.4
SLOW_BURN_RATE = 6.0

DEFAULT_WINDOW_S = 300.0
DEFAULT_BUDGET = 0.01

# window geometry, scaled to the objective's window W: the slow pair
# is (W, W/12) — the workbook's 6h/30m shape — and the fast pair
# (W/6, W/72) — the 1h/5m shape. Short windows gate alert RESET: a
# recovered tenant's short burn drops immediately, so the alert
# clears without draining the long window.
_WINDOW_FRACS = (("long", 1.0), ("short", 1.0 / 12.0),
                 ("fast_long", 1.0 / 6.0), ("fast_short", 1.0 / 72.0))

_NAME_RE = re.compile(r"^[a-z0-9_.\-]+$")


def latency_bounds(target_s: float) -> List[float]:
    """SLO-aware explicit histogram bounds for a latency objective:
    fine resolution around the target with the target itself ON a
    bucket edge, so the cumulative bucket walk judges "observation <=
    target" exactly (the bucket-boundary error at the target is zero;
    everywhere else it is bounded by one bucket width). Pass to
    ``registry.histogram(name, bounds=...)`` BEFORE observations."""
    t = float(target_s)
    check(t > 0, f"slo: latency target must be > 0, got {target_s!r}")
    return [round(t * f, 9)
            for f in (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875,
                      1.0, 1.25, 1.5, 2.0, 3.0, 4.0, 8.0)]


class _Objective:
    """One declared objective's ledger (engine-lock protected)."""

    __slots__ = ("name", "metric", "target_s", "window_s", "budget",
                 "tenant", "samples")

    def __init__(self, name: str, metric: str, target_s: float,
                 window_s: float, budget: float,
                 tenant: Optional[str]):
        self.name = name
        self.metric = metric
        self.target_s = target_s
        self.window_s = window_s
        self.budget = budget
        self.tenant = tenant
        # cumulative (monotonic t, good, total) samples; window
        # attainment is a difference of two samples, so no
        # per-observation bookkeeping ever happens
        self.samples: deque = deque()


class SloEngine:
    """Objectives, windowed attainment, budget burn (module docstring).

    A daemon sampler thread differences the histograms every
    ``period_s``; with no objectives registered a tick is a no-op
    (the <2% off-cost smoke gate, tests/test_slo.py)."""

    def __init__(self, registry=None, period_s: float = 1.0):
        check(period_s > 0, "slo: period_s must be > 0")
        self._registry = registry if registry is not None else _METRICS
        self.period_s = float(period_s)
        self._lock = threading.Lock()
        self._objectives: Dict[str, _Objective] = {}
        # rows computed at the last sample(): the collector and
        # verdicts() read this cache so a /metrics scrape never pays
        # for a fresh histogram walk
        self._last_rows: Dict[str, Dict[str, Any]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._metrics_key = self._registry.register(
            "slo", self, SloEngine._collect)

    # ------------------------------------------------- declarations

    def register(self, name: str, *, metric: str, target_s: float,
                 window_s: float = DEFAULT_WINDOW_S,
                 budget: float = DEFAULT_BUDGET,
                 tenant: Optional[str] = None) -> str:
        """Declare (or re-declare) an objective: observations of
        histogram ``metric`` stay <= ``target_s`` seconds over
        ``window_s``, with a ``budget`` fraction allowed to miss.
        Registration snapshots the metric's CURRENT cumulative counts
        as the baseline — traffic before the declaration is never
        judged against it."""
        check(bool(_NAME_RE.match(name or "")),
              f"slo: objective name {name!r} must match "
              f"{_NAME_RE.pattern}")
        check(float(target_s) > 0,
              f"slo objective {name!r}: target_s must be > 0")
        check(float(window_s) > 0,
              f"slo objective {name!r}: window_s must be > 0")
        check(0 < float(budget) < 1,
              f"slo objective {name!r}: budget must be in (0, 1)")
        o = _Objective(name, str(metric), float(target_s),
                       float(window_s), float(budget), tenant)
        now = time.monotonic()
        o.samples.append((now,) + self._counts(o))
        with self._lock:
            self._objectives[name] = o
            self._last_rows[name] = self._row_locked(o, now)
        return name

    def unregister(self, name: str) -> None:
        with self._lock:
            self._objectives.pop(name, None)
            self._last_rows.pop(name, None)

    def objectives(self) -> List[str]:
        with self._lock:
            return sorted(self._objectives)

    # --------------------------------------------------- judgment

    def _counts(self, o: _Objective) -> tuple:
        """Cumulative (good, total) of the objective's histogram right
        now: good = observations at or under the target, from the
        cumulative bucket walk. A bucket straddling the target counts
        as bad — judgment error is bounded by one bucket width, zero
        when the target sits on a bound (latency_bounds). peek, never
        get-or-create: an objective must not materialize its metric."""
        h = self._registry.peek_histogram(o.metric)
        if h is None:
            return 0, 0
        s = h.summary()
        good = 0
        lim = o.target_s * (1.0 + 1e-9)
        for ub, n in (s.get("buckets") or {}).items():
            try:
                if float(ub) <= lim:
                    good += int(n)
            except (TypeError, ValueError):
                continue
        return good, int(s.get("count") or 0)

    def sample(self, now: Optional[float] = None) -> float:
        """One sampling pass: append a cumulative sample per objective,
        prune past the long window, refresh the cached rows and the
        per-objective ``slo.*`` gauges. Returns the pass timestamp
        (monotonic; pass ``now`` explicitly for deterministic tests)."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            objectives = list(self._objectives.values())
        for o in objectives:
            counts = self._counts(o)
            with self._lock:
                o.samples.append((now,) + counts)
                # keep one sample OLDER than the long window as its
                # baseline; everything older than that is dead weight
                while (len(o.samples) > 2
                       and o.samples[1][0] <= now - o.window_s):
                    o.samples.popleft()
                row = self._row_locked(o, now)
                self._last_rows[o.name] = row
            self._export_gauges(o.name, row)
        return now

    def _window_counts_locked(self, o: _Objective, now: float,
                              window_s: float) -> tuple:
        """(good, total) inside the trailing window: newest cumulative
        sample minus the newest sample at or before the window start
        (falling back to the oldest sample — a not-yet-full window
        judges what it has, from the registration baseline)."""
        if not o.samples:
            return 0, 0
        cur = o.samples[-1]
        base = None
        start = now - window_s
        for s in o.samples:
            if s[0] <= start:
                base = s
            else:
                break
        if base is None:
            base = o.samples[0]
        return max(0, cur[1] - base[1]), max(0, cur[2] - base[2])

    def _row_locked(self, o: _Objective, now: float) -> Dict[str, Any]:
        windows: Dict[str, Any] = {}
        for label, frac in _WINDOW_FRACS:
            w = o.window_s * frac
            good, total = self._window_counts_locked(o, now, w)
            sli = (good / total) if total else None
            burn = ((1.0 - sli) / o.budget) if sli is not None else None
            windows[label] = {
                "window_s": round(w, 3),
                "good": good,
                "total": total,
                "attainment": (round(sli, 6) if sli is not None
                               else None),
                "burn": round(burn, 4) if burn is not None else None,
            }
        return self._judge(o.name, o.metric, o.target_s, o.window_s,
                           o.budget, o.tenant, windows)

    @staticmethod
    def _judge(name: str, metric: str, target_s: float,
               window_s: float, budget: float, tenant: Optional[str],
               windows: Dict[str, Any]) -> Dict[str, Any]:
        """Alert + budget arithmetic over computed window counts (the
        ONE implementation — merge_views re-judges merged gang counts
        through here, so a gang objective obeys the same rules)."""

        def _pair_fires(long_label: str, short_label: str,
                        rate: float) -> bool:
            bl = (windows.get(long_label) or {}).get("burn")
            bs = (windows.get(short_label) or {}).get("burn")
            return (bl is not None and bs is not None
                    and bl >= rate and bs >= rate)

        fast = _pair_fires("fast_long", "fast_short", FAST_BURN_RATE)
        slow = _pair_fires("long", "short", SLOW_BURN_RATE)
        att = (windows.get("long") or {}).get("attainment")
        remaining = (round(1.0 - (1.0 - att) / budget, 6)
                     if att is not None else None)
        return {
            "metric": metric,
            "target_s": target_s,
            "window_s": window_s,
            "budget": budget,
            "tenant": tenant,
            "attainment": att,
            "budget_remaining": remaining,
            "windows": windows,
            "alerts": {"fast": fast, "slow": slow,
                       "firing": fast or slow},
        }

    def _export_gauges(self, name: str, row: Dict[str, Any]) -> None:
        g = self._registry.gauge
        g(f"slo.{name}.attainment").set(row["attainment"])
        g(f"slo.{name}.budget_remaining").set(row["budget_remaining"])
        g(f"slo.{name}.burn").set(row["windows"]["long"]["burn"])
        g(f"slo.{name}.fast_burn").set(row["alerts"]["fast"])
        g(f"slo.{name}.slow_burn").set(row["alerts"]["slow"])

    # ------------------------------------------------------- reads

    def view(self, sample: bool = True) -> Dict[str, Any]:
        """The ``GET /slo`` payload (and ``slo.json`` in flight
        bundles). ``sample=True`` takes a fresh pass first so a reader
        never judges stale counts."""
        if sample:
            self.sample()
        with self._lock:
            return {"schema": SLO_SCHEMA,
                    "fast_burn_rate": FAST_BURN_RATE,
                    "slow_burn_rate": SLOW_BURN_RATE,
                    "objectives": {n: dict(r) for n, r in
                                   sorted(self._last_rows.items())}}

    def _collect(self) -> Dict[str, Any]:
        """Registry-collector shape: the cached rows (numeric leaves
        flatten onto /metrics; the full rows ride /metrics.json so
        rank 0 can merge_views the gang)."""
        with self._lock:
            rows = {n: dict(r) for n, r in self._last_rows.items()}
        return {"schema": SLO_SCHEMA, "count": len(rows),
                "firing": sum(1 for r in rows.values()
                              if r["alerts"]["firing"]),
                "objectives": rows}

    def verdicts(self, epoch: Optional[int] = None
                 ) -> List[Dict[str, Any]]:
        """``slo``-bound verdicts (obs.analyze VERDICT_KEYS shape) for
        every objective with a FIRING alert — what /analyze attaches
        and the PR-12 controller will consume. Empty when healthy."""
        from dmlc_tpu.obs import analyze as _an
        with self._lock:
            rows = {n: dict(r) for n, r in self._last_rows.items()}
        return [_an.slo_verdict(name, row, epoch=epoch)
                for name, row in sorted(rows.items())
                if row["alerts"]["firing"]]

    # --------------------------------------------------- lifecycle

    def start(self) -> "SloEngine":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="dmlc_tpu.obs.SloEngine")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                if self._objectives:
                    self.sample()
            except Exception:  # noqa: BLE001 — the sampler survives
                pass

    def close(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
        if self._metrics_key is not None:
            self._registry.unregister(self._metrics_key)
            self._metrics_key = None


# ------------------------------------------------------- gang rollup

def merge_views(views: List[Dict[str, Any]],
                unreachable: Iterable[Any] = ()) -> Dict[str, Any]:
    """Rank-0 rollup: judge each objective on the gang's MERGED window
    counts (good/total summed across the ranks that reported it), then
    re-run the same alert arithmetic — a gang-level objective is
    judged on merged samples, not on a vote of per-rank verdicts.
    ``unreachable`` ranks mark the rollup (and every objective row)
    ``incomplete``: the merged numbers still render, flagged as a
    subset, never dressed up as the gang."""
    unreachable = [str(u) for u in unreachable]
    incomplete = bool(unreachable)
    by_name: Dict[str, List[Dict[str, Any]]] = {}
    for v in views:
        if not isinstance(v, dict):
            continue
        for name, row in (v.get("objectives") or {}).items():
            if isinstance(row, dict):
                by_name.setdefault(str(name), []).append(row)
    objectives: Dict[str, Any] = {}
    for name, rows in sorted(by_name.items()):
        spec = rows[0]
        windows: Dict[str, Any] = {}
        budget = float(spec.get("budget") or DEFAULT_BUDGET)
        for label, _frac in _WINDOW_FRACS:
            good = total = 0
            w = None
            for r in rows:
                win = (r.get("windows") or {}).get(label) or {}
                good += int(win.get("good") or 0)
                total += int(win.get("total") or 0)
                if w is None and win.get("window_s") is not None:
                    w = win["window_s"]
            sli = (good / total) if total else None
            burn = ((1.0 - sli) / budget) if sli is not None else None
            windows[label] = {
                "window_s": w,
                "good": good,
                "total": total,
                "attainment": (round(sli, 6) if sli is not None
                               else None),
                "burn": round(burn, 4) if burn is not None else None,
            }
        row = SloEngine._judge(
            name, spec.get("metric"), spec.get("target_s"),
            spec.get("window_s"), budget, spec.get("tenant"), windows)
        row["ranks"] = len(rows)
        row["incomplete"] = incomplete
        objectives[name] = row
    return {"schema": SLO_SCHEMA, "incomplete": incomplete,
            "unreachable": unreachable, "ranks": len(views),
            "objectives": objectives}


def gang_view(merged_snap: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The gang SLO rollup from a ``scrape_gang()`` merged snapshot:
    pull every reachable rank's ``slo`` collector payload and
    :func:`merge_views` them, with the scrape's unreachable ports
    marking the rollup incomplete. None when no rank carries an SLO
    section and nothing was unreachable."""
    views = []
    for w in (merged_snap.get("workers") or {}).values():
        v = (w.get("collectors") or {}).get("slo")
        if isinstance(v, dict) and v.get("objectives"):
            views.append(v)
    unreachable = sorted(merged_snap.get("unreachable") or {})
    if not views and not unreachable:
        return None
    return merge_views(views, unreachable=unreachable)


# ------------------------------------------------- process wiring
# (the serve/flight/history/control install contract)

_active: Optional[SloEngine] = None
_lock = threading.Lock()


def active() -> Optional[SloEngine]:
    return _active


def install(engine: Optional[SloEngine] = None,
            **opts: Any) -> SloEngine:
    """Install the process SLO engine (idempotent: a second call
    returns the running one, like obs.serve.serve)."""
    global _active
    with _lock:
        if _active is not None:
            return _active
        _active = (engine if engine is not None
                   else SloEngine(**opts)).start()
        return _active


def uninstall() -> None:
    global _active
    with _lock:
        eng, _active = _active, None
    if eng is not None:
        eng.close()


def parse_objectives(raw: str) -> List[Dict[str, Any]]:
    """Parse the declaration grammar: ``;``-separated objectives, each
    a ``,``-separated k=v list with keys ``name``/``metric``/``target``
    (required) and ``window``/``budget``/``tenant`` (optional) —
    ``name=victim,metric=tenant.victim.batch_s,target=0.15,window=300,
    budget=0.01``. Raises ValueError on anything malformed."""
    out: List[Dict[str, Any]] = []
    for decl in raw.split(";"):
        decl = decl.strip()
        if not decl:
            continue
        spec: Dict[str, Any] = {}
        for part in decl.split(","):
            k, eq, v = part.partition("=")
            k, v = k.strip(), v.strip()
            if not eq or not v:
                raise ValueError(part)
            if k in ("name", "metric", "tenant"):
                spec[k] = v
            elif k == "target":
                spec["target_s"] = float(v)
            elif k == "window":
                spec["window_s"] = float(v)
            elif k == "budget":
                spec["budget"] = float(v)
            else:
                raise ValueError(k)
        if not {"name", "metric", "target_s"} <= set(spec):
            raise ValueError(decl)
        out.append(spec)
    return out


def install_if_env() -> Optional[SloEngine]:
    """Gang-worker hook: install under ``DMLC_TPU_SLO`` — "1"/"true"
    for an empty engine (declarations arrive at runtime), or the
    :func:`parse_objectives` grammar — else no-op
    (``launch_local(slo=...)`` sets the var per worker). A malformed
    declaration degrades to a warning and an empty engine: the
    telemetry opt-in must never take down the job it watches."""
    raw = os.environ.get(ENV_SLO, "").strip()
    if not raw or raw in ("0", "false"):
        return None
    specs: List[Dict[str, Any]] = []
    if raw not in ("1", "true"):
        try:
            specs = parse_objectives(raw)
        except ValueError:
            from dmlc_tpu.obs.log import warn_once
            warn_once("slo-env-malformed",
                      f"obs.slo: malformed {ENV_SLO}={raw!r} (want '1' "
                      "or 'name=...,metric=...,target=0.15[,window=300]"
                      "[,budget=0.01][;...]'); installing an empty "
                      "engine", all_ranks=True)
            specs = []
    eng = install()
    for spec in specs:
        eng.register(spec.pop("name"), **spec)
    return eng
