"""Live telemetry plane: an in-process, stdlib-only HTTP status server.

Every obs surface before this module required a cooperative exit (trace
files, metrics snapshots) or an up-front flag (``--trace``) — a
production data plane is monitored while it runs. One daemon thread per
rank serves:

- ``GET /metrics`` — Prometheus text exposition (0.0.4) rendered from
  :meth:`~dmlc_tpu.obs.metrics.MetricsRegistry.snapshot`: counters as
  ``dmlc_*_total``, numeric gauges as gauges, STRING gauges as labeled
  info-style series (``dmlc_<name>_info{value="pages"} 1`` — a
  ``Gauge.set("pages")`` must not emit an invalid exposition line),
  any other non-numeric gauge skipped and counted in
  ``dmlc_obs_export_skipped_total``, histograms with cumulative
  ``_bucket{le=...}`` series, and collector dicts flattened to numeric
  leaves labeled by collector/key;
- ``GET /metrics.json`` — the raw versioned snapshot (what
  :func:`scrape_gang` fetches to merge a gang);
- ``GET /healthz`` — liveness + the instrumented pulls blocked right
  now (:func:`dmlc_tpu.obs.watchdog.current_waits`);
- ``GET /stacks`` — an all-thread stack dump;
- ``GET /trace?seconds=N`` — an on-demand bounded Perfetto capture of
  the RUNNING pipeline: installs a recorder for N seconds when none is
  active (restoring the flight ring after), or lets an active ring
  accumulate N more seconds, then returns the Chrome trace-event JSON;
- ``GET /history[?seconds=N]`` — the shared time-series ring
  (:mod:`dmlc_tpu.obs.timeseries`): this rank's metric history,
  optionally trimmed to the trailing N seconds;
- ``GET /gang[?seconds=N]`` — the gang aggregator's merged view
  (:mod:`dmlc_tpu.obs.aggregate`, rank 0 / launcher): per-rank series,
  rollups, explicit unreachable-rank gaps; plus a ``membership``
  section (roster, ranks, membership epoch) whenever this process has
  joined a :mod:`dmlc_tpu.rendezvous` gang;
- ``GET /tenants`` — the multi-tenant scheduler's per-tenant rows
  (:mod:`dmlc_tpu.pipeline.scheduler`): budget, live pipelines,
  credits/deficit, queue share and occupancy, batch p50/p99, streaming
  watermark, last bound verdict (404 with an enable hint until a
  scheduler is installed, like ``/history``);
- ``GET /slo`` — declared objectives judged live
  (:mod:`dmlc_tpu.obs.slo`): per-objective windowed attainment,
  error-budget remaining, and multi-rate burn alerts (404 with an
  enable hint until an objective is registered, like ``/history``);
- ``GET /analyze`` — a bottleneck-attribution verdict
  (:mod:`dmlc_tpu.obs.analyze`) over the last completed pipeline
  epoch's stage stats + the current registry snapshot; any FIRING
  SLO alerts ride along as ``slo_verdicts``;
- ``GET /control[?last=N]`` — the verdict-driven controller's state
  and decision ledger (:mod:`dmlc_tpu.obs.control`): every knob move,
  freeze, and no-op with the verdict evidence that caused it (404
  with an enable hint until a controller is installed, like
  ``/history``);
- ``GET /profile[?seconds=N&hz=M]`` — the sampling profiler's merged
  Python+native flamegraph (:mod:`dmlc_tpu.obs.profile`): the
  continuous trie, or an on-demand burst capture of the next N
  seconds at M Hz (404 with an enable hint when no profiler is
  installed, like ``/history``);
- ``GET /pages/<entry>`` — the gang peer DATA plane (ROADMAP item 5):
  serves one committed, fingerprint-fresh page-store entry's bytes
  (``Range: bytes=a-b`` honored with a 206) under a refcounted pin,
  stamping the entry's fingerprint and codec tag as response headers
  so the peer client (:mod:`dmlc_tpu.io.objstore.peer`) can validate
  before trusting a byte. Stale-stamped, uncommitted, or
  unsafely-named entries answer 404 — a peer can degrade to the wire,
  it must never be fed a wrong page. This endpoint is why
  ``ThreadingHTTPServer`` matters: a slow ``/pages`` body transfer
  runs on its own handler thread and cannot starve ``/healthz`` or
  ``/metrics`` scrapes.

``launch_local(serve_ports=[...])`` hands every worker a port via
``DMLC_TPU_SERVE_PORT`` (workers opt in with one :func:`serve_if_env`
call) plus the full gang list via ``DMLC_TPU_SERVE_PORTS`` so rank 0 —
or the launcher — can :func:`scrape_gang` the live processes into one
merged snapshot. "Rerun it with --trace and hope it reproduces"
becomes "curl the rank that is slow right now".
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from dmlc_tpu.obs import rpc as _rpc
from dmlc_tpu.obs.metrics import (
    REGISTRY, MetricsRegistry, merge_snapshots,
)

__all__ = ["StatusServer", "serve", "serve_if_env", "render_prometheus",
           "scrape", "scrape_gang", "ENV_SERVE_PORT", "ENV_SERVE_PORTS"]

# env contract (parallel.launch.launch_local(serve_ports=...) sets both)
ENV_SERVE_PORT = "DMLC_TPU_SERVE_PORT"    # this worker's port
ENV_SERVE_PORTS = "DMLC_TPU_SERVE_PORTS"  # comma-joined gang ports

# /trace?seconds=N is clamped here: the handler thread sleeps for the
# capture window and an unbounded N would pin it (and the client)
MAX_TRACE_CAPTURE_S = 60.0

# /pages freshness verdicts are cached briefly: re-statting the origin
# per served block (a HEAD for obj:// sources) would erode the 1/N
# wire saving the peer tier delivers. A stale page can thus be served
# for up to the TTL — bounded and safe: entry names are etag-keyed (a
# changed object changes the requested name) and the peer CLIENT
# independently validates the stamped fingerprint before trusting a
# byte. Keyed by (root, name, stamp), so a re-stamped entry is
# re-judged immediately.
PAGE_FRESH_TTL_S = 2.0
_page_fresh_cache: Dict[tuple, tuple] = {}

_name_ok = re.compile(r"[^a-z0-9_]")


def _prom_name(name: str, prefix: str = "dmlc_") -> str:
    """Registry name -> Prometheus metric name ([a-z0-9_], prefixed)."""
    return prefix + _name_ok.sub("_", name.lower())


def _prom_label(value: str) -> str:
    """Escape a label value per the exposition format (bounded: a
    runaway state string must not bloat every scrape)."""
    return (str(value)[:200].replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _num(v: Any) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    return repr(float(v)) if isinstance(v, float) else str(v)


def _flatten_numeric(prefix: str, value: Any,
                     out: List[tuple]) -> None:
    """Collector payloads are arbitrary JSON; keep numeric leaves as
    (dotted.key.path, number) and drop the rest silently — collectors
    carry strings by design (replay tiers, error notes)."""
    if isinstance(value, dict):
        for k, v in value.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            _flatten_numeric(key, v, out)
    elif isinstance(value, (list, tuple)):
        for i, v in enumerate(value):
            _flatten_numeric(f"{prefix}.{i}", v, out)
    elif _is_num(value) or isinstance(value, bool):
        out.append((prefix, value))


def render_prometheus(snap: Dict[str, Any],
                      registry: Optional[MetricsRegistry] = None) -> str:
    """One snapshot -> Prometheus text exposition (format 0.0.4).

    The rendered families (names/types/HELP lines pinned by
    tests/test_obs_live.py):

    - ``dmlc_obs_info{rank=...,pid=...,schema=...} 1`` — who answered;
    - counters  -> ``dmlc_<name>_total`` (TYPE counter);
    - gauges    -> numeric: ``dmlc_<name>`` (TYPE gauge); string:
      ``dmlc_<name>_info{value="..."} 1``; anything else (snapshot()
      reprs unknown objects but passes dicts/lists through) is
      SKIPPED and counted in ``dmlc_obs_export_skipped_total`` — a
      structured value has no valid single exposition line;
    - histograms -> ``_bucket{le=...}`` cumulative + ``_sum``/``_count``;
    - collectors -> ``dmlc_collector_value{collector=...,key=...}``
      for every numeric leaf.
    """
    reg = registry if registry is not None else REGISTRY
    skipped = 0
    lines: List[str] = [
        "# HELP dmlc_obs_info Identity of the serving process.",
        "# TYPE dmlc_obs_info gauge",
        f'dmlc_obs_info{{rank="{_prom_label(snap.get("rank"))}",'
        f'pid="{_prom_label(snap.get("pid"))}",'
        f'schema="{_prom_label(snap.get("schema"))}"}} 1',
    ]
    for name, value in sorted((snap.get("counters") or {}).items()):
        if name == "obs.export_skipped":
            continue  # rendered once at the end with THIS render's
            # skips included — emitting it here too would duplicate
            # the family, which Prometheus rejects outright
        pn = _prom_name(name) + "_total"
        lines.append(f"# HELP {pn} Counter {name} "
                     "(dmlc_tpu.obs.metrics).")
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {_num(value)}")
    for name, value in sorted((snap.get("gauges") or {}).items()):
        pn = _prom_name(name)
        if _is_num(value) or isinstance(value, bool):
            lines.append(f"# HELP {pn} Gauge {name} "
                         "(dmlc_tpu.obs.metrics).")
            lines.append(f"# TYPE {pn} gauge")
            lines.append(f"{pn} {_num(value)}")
        elif isinstance(value, str):
            # info-style labeled series: the VALUE rides as a label
            lines.append(f"# HELP {pn}_info Gauge {name} "
                         "(non-numeric state, value in label).")
            lines.append(f"# TYPE {pn}_info gauge")
            lines.append(f'{pn}_info{{value="{_prom_label(value)}"}} 1')
        elif value is None:
            continue  # never-set gauge: nothing to export
        else:
            skipped += 1
    for name, h in sorted((snap.get("histograms") or {}).items()):
        pn = _prom_name(name)
        lines.append(f"# HELP {pn} Histogram {name} "
                     "(dmlc_tpu.obs.metrics, log2 buckets).")
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        # snapshot buckets are keyed by repr(upper_bound), per-bucket
        # counts; the exposition wants cumulative le= series
        try:
            buckets = sorted((float(k), v)
                             for k, v in (h.get("buckets") or {}).items())
        except (TypeError, ValueError):
            buckets = []
        for ub, count in buckets:
            cum += count
            lines.append(f'{pn}_bucket{{le="{repr(ub)}"}} {cum}')
        lines.append(f'{pn}_bucket{{le="+Inf"}} {h.get("count", 0)}')
        lines.append(f"{pn}_sum {_num(h.get('sum') or 0)}")
        lines.append(f"{pn}_count {h.get('count', 0)}")
        # bucket-estimated quantiles as sibling gauge families (a
        # histogram family admits no extra series of its own)
        for qk in ("p50", "p99"):
            qv = h.get(qk)
            if _is_num(qv):
                qn = f"{pn}_{qk}"
                lines.append(f"# HELP {qn} Histogram {name} {qk} "
                             "estimate (log2 buckets, clamped to "
                             "min/max).")
                lines.append(f"# TYPE {qn} gauge")
                lines.append(f"{qn} {_num(qv)}")
    leaves: List[tuple] = []
    for cname, payload in sorted((snap.get("collectors") or {}).items()):
        flat: List[tuple] = []
        _flatten_numeric("", payload, flat)
        leaves.extend((cname, key, v) for key, v in flat)
    if leaves:
        lines.append("# HELP dmlc_collector_value Numeric leaves of "
                     "registered stats() collectors.")
        lines.append("# TYPE dmlc_collector_value gauge")
        for cname, key, v in leaves:
            lines.append(
                f'dmlc_collector_value{{collector="{_prom_label(cname)}"'
                f',key="{_prom_label(key)}"}} {_num(v)}')
    if skipped:
        reg.counter("obs.export_skipped").inc(skipped)
    total = reg.counter("obs.export_skipped").value
    if total:
        pn = "dmlc_obs_export_skipped_total"
        lines.append(f"# HELP {pn} Gauge values not renderable in the "
                     "exposition (neither numeric nor string).")
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {total}")
    return "\n".join(lines) + "\n"


def _thread_stacks() -> str:
    """All-thread stack dump (the watchdog's report helper)."""
    from dmlc_tpu.obs.watchdog import _thread_stacks as dump
    return dump()


def _capture_trace(seconds: float) -> Dict[str, Any]:
    """On-demand bounded capture of the running process: when no
    recorder is active, install one for the window (start() displaces
    the flight ring if installed; stop() reinstates it); when a ring is
    already live (flight fallback or an explicit trace) let it
    accumulate the window and export its CURRENT contents without
    disturbing it."""
    from dmlc_tpu.obs import trace as _trace
    from dmlc_tpu.obs.export import chrome_events
    seconds = max(0.0, min(float(seconds), MAX_TRACE_CAPTURE_S))
    rec = _trace.active()
    owned = rec is None or rec is _trace.fallback()
    if rec is None:
        rec = _trace.start()
    if seconds:
        time.sleep(seconds)
    if owned and _trace.active() is rec and rec is not _trace.fallback():
        _trace.stop()
    return {
        "traceEvents": chrome_events(rec),
        "displayTimeUnit": "ms",
        "otherData": {"recorded": rec.recorded, "dropped": rec.dropped,
                      "capture_s": seconds},
    }


class _Handler(BaseHTTPRequestHandler):
    """Routes; the owning StatusServer rides on the server object."""

    server_version = "dmlc-tpu-obs/1"

    def log_message(self, format, *args):  # noqa: A002 — base signature
        pass  # scrapes must not spam stderr

    def setup(self):
        # arrival stamp for the server span's queue phase: everything
        # between the connection being handed to this thread and
        # do_GET starting (request-line/header parse included)
        self._rpc_arrival = time.perf_counter()
        super().setup()

    def _echo_trace(self) -> None:
        """Echo an inbound trace context plus the server handle time
        so far (obs.rpc headers) — the client folds the echo into its
        edge table to split wire wait from server work. Untraced
        requests get no extra headers. Call between send_response()
        and end_headers()."""
        ctx = getattr(self, "_rpc_ctx", None)
        if ctx is None:
            return
        self._rpc_sent = time.perf_counter()
        self.send_header(_rpc.TRACE_HEADER, _rpc.serialize(ctx))
        self.send_header(
            _rpc.HANDLE_HEADER,
            str(round((self._rpc_sent - self._rpc_t0) * 1e6, 1)))

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self._echo_trace()
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, payload: Any, code: int = 200) -> None:
        self._send(code, json.dumps(payload).encode(),
                   "application/json")

    def _serve_page(self, owner: "StatusServer", name: str) -> None:
        """The /pages/<entry> peer data plane: one committed,
        fingerprint-fresh page-store entry's stored bytes, under a
        refcounted pin so eviction cannot pull the page mid-transfer.
        The stored bytes may be a codec frame — the client decodes;
        headers carry the stamped fingerprint + codec tag for the
        client's own validation. Ranges (``Range: bytes=a-b``) apply
        to the STORED entry bytes and answer 206."""
        from urllib.parse import unquote

        from dmlc_tpu.io.pagestore import fingerprint_fresh
        name = unquote(name)
        # entry names are flat files in the store root: anything
        # path-shaped is rejected before it touches the filesystem
        if (not name or "/" in name or "\\" in name or ".." in name
                or name.startswith(".")):
            self._send_json({"error": "invalid page name"}, code=404)
            return
        store = owner.pages_store()
        meta = store.stamp(name)
        if meta is None:
            # no sidecar = not a committed store entry (or a bare
            # legacy file whose staleness nobody can judge): never
            # serve it to a peer
            self._send_json({"error": "no such committed page",
                             "entry": name}, code=404)
            return
        fp = meta.get("fingerprint")
        cache_key = (store.root, name, json.dumps(fp))
        hit = _page_fresh_cache.get(cache_key)
        if hit is not None and time.monotonic() - hit[0] \
                < PAGE_FRESH_TTL_S:
            fresh = hit[1]
        else:
            fresh = fingerprint_fresh(fp)
            if len(_page_fresh_cache) > 1024:
                _page_fresh_cache.clear()  # bounded, coarse
            _page_fresh_cache[cache_key] = (time.monotonic(), fresh)
        if fresh is False:
            self._send_json({"error": "stale page fingerprint",
                             "entry": name}, code=404)
            return
        store.pin(name)
        try:
            s = store.open_read(name)
            if s is None:
                self._send_json({"error": "no such committed page",
                                 "entry": name}, code=404)
                return
            with s:
                data = s.read_all()
            total = len(data)
            code = 200
            content_range = None
            rng = self.headers.get("Range")
            m = re.match(r"bytes=(\d+)-(\d*)$", (rng or "").strip())
            if m:
                lo = int(m.group(1))
                hi = int(m.group(2)) + 1 if m.group(2) else total
                hi = min(hi, total)
                if lo >= hi:
                    self._send_json(
                        {"error": f"unsatisfiable range {rng!r}",
                         "size": total}, code=416)
                    return
                data = data[lo:hi]
                code = 206
                content_range = f"bytes {lo}-{hi - 1}/{total}"
            self.send_response(code)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(data)))
            if content_range:
                self.send_header("Content-Range", content_range)
            self.send_header("X-Dmlc-Fingerprint", json.dumps(fp))
            self.send_header("X-Dmlc-Codec",
                             str(meta.get("codec", "raw")))
            self._echo_trace()
            self.end_headers()
            self.wfile.write(data)
            owner.registry.counter("objstore.peer.served").inc()
            owner.registry.counter(
                "objstore.peer.served_bytes").inc(len(data))
        finally:
            store.unpin(name)

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        url = urlparse(self.path)
        # bind the inbound trace context (if any): the echo headers and
        # the server span below both key off it
        self._rpc_ctx = _rpc.extract(self.headers)
        self._rpc_t0 = time.perf_counter()
        self._rpc_sent: Optional[float] = None
        try:
            owner: "StatusServer" = self.server.status_server
            if url.path == "/metrics":
                body = render_prometheus(owner.registry.snapshot(),
                                         owner.registry)
                self._send(200, body.encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif url.path == "/metrics.json":
                self._send_json(owner.registry.snapshot())
            elif url.path == "/healthz":
                self._send_json(owner.health())
            elif url.path == "/stacks":
                self._send(200, _thread_stacks().encode(),
                           "text/plain; charset=utf-8")
            elif url.path == "/trace":
                q = parse_qs(url.query)
                seconds = float(q.get("seconds", ["1"])[0])
                self._send_json(_capture_trace(seconds))
            elif url.path == "/history":
                from dmlc_tpu.obs import timeseries as _ts
                ring = _ts.active()
                if ring is None:
                    self._send_json(
                        {"error": "no timeseries ring installed",
                         "hint": "set DMLC_TPU_HISTORY_S (launch_local"
                                 "(history_s=...)) or call "
                                 "obs.timeseries.install()"},
                        code=404)
                else:
                    q = parse_qs(url.query)
                    raw = q.get("seconds", [None])[0]
                    last_s = float(raw) if raw else None
                    self._send_json(ring.to_dict(last_s=last_s))
            elif url.path == "/gang":
                from dmlc_tpu.obs import aggregate as _agg
                agg = _agg.active()
                membership = None
                try:
                    from dmlc_tpu import rendezvous as _rndv
                    cli = _rndv.active()
                    if cli is not None:
                        membership = cli.view()
                except Exception:  # noqa: BLE001 — membership rows
                    pass           # are additive, never a 500
                if agg is None and membership is None:
                    self._send_json(
                        {"error": "no gang aggregator or rendezvous "
                                  "membership installed",
                         "hint": "set DMLC_TPU_GANG_POLL_S (launch_"
                                 "local(gang_poll_s=...)) or join a "
                                 "rendezvous (launch_local("
                                 "rendezvous=True) + dmlc_tpu."
                                 "rendezvous.install_if_env())"},
                        code=404)
                else:
                    if agg is not None:
                        q = parse_qs(url.query)
                        raw = q.get("seconds", [None])[0]
                        last_s = float(raw) if raw else None
                        body = agg.view(last_s=last_s)
                    else:
                        body = {"schema": 0}
                    if membership is not None:
                        # the elastic half of the gang story: who is
                        # in, at which rank, under which membership
                        # epoch (docs/rendezvous.md)
                        body["membership"] = membership
                    self._send_json(body)
            elif url.path == "/control":
                from dmlc_tpu.obs import control as _control
                ctl = _control.active()
                if ctl is None:
                    self._send_json(
                        {"error": "no controller installed",
                         "hint": "set DMLC_TPU_CONTROL=1 (launch_"
                                 "local(control=True)) or call "
                                 "obs.control.install()"},
                        code=404)
                else:
                    q = parse_qs(url.query)
                    raw = q.get("last", [None])[0]
                    last = int(raw) if raw else None
                    self._send_json(ctl.to_dict(last=last))
            elif url.path == "/tenants":
                from dmlc_tpu.pipeline import scheduler as _sched
                sched = _sched.active()
                if sched is None:
                    self._send_json(
                        {"error": "no pipeline scheduler installed",
                         "hint": "set DMLC_TPU_SCHED=1 (launch_local"
                                 "(scheduler=True)) or call "
                                 "pipeline.scheduler.install()"},
                        code=404)
                else:
                    self._send_json(sched.to_dict())
            elif url.path == "/slo":
                from dmlc_tpu.obs import slo as _slo
                eng = _slo.active()
                if eng is None or not eng.objectives():
                    self._send_json(
                        {"error": "no SLO objectives registered",
                         "hint": "set DMLC_TPU_SLO (launch_local"
                                 "(slo=...)), declare via "
                                 "scheduler.add_tenant(slo=...), or "
                                 "call obs.slo.install().register()"},
                        code=404)
                else:
                    self._send_json(eng.view())
            elif url.path == "/shuffle":
                from dmlc_tpu import shuffle as _shuffle
                doc = _shuffle.view()
                if doc is None:
                    self._send_json(
                        {"error": "no global shuffle active",
                         "hint": "Pipeline.from_uri(...).shuffle("
                                 "global_seed=...) or construct "
                                 "dmlc_tpu.shuffle.GlobalShuffleSplit "
                                 "in this process"},
                        code=404)
                else:
                    self._send_json(doc)
            elif url.path == "/analyze":
                verdict = owner.analyze_verdict()
                # a burning declared objective rides along: the stage
                # verdict says WHERE time goes, the slo verdicts say
                # which promises that breaks (obs.slo)
                svs = []
                try:
                    from dmlc_tpu.obs import slo as _slo
                    eng = _slo.active()
                    if eng is not None:
                        svs = eng.verdicts()
                except Exception:  # noqa: BLE001
                    svs = []
                if verdict is None and not svs:
                    self._send_json(
                        {"error": "no pipeline stats to attribute "
                                  "(no registered pipeline collector "
                                  "has completed an epoch yet)"},
                        code=404)
                elif verdict is None:
                    self._send_json({"slo_verdicts": svs})
                else:
                    if svs:
                        verdict = dict(verdict)
                        verdict["slo_verdicts"] = svs
                    self._send_json(verdict)
            elif url.path == "/profile":
                from dmlc_tpu.obs import profile as _prof
                prof = _prof.active()
                if prof is None:
                    self._send_json(
                        {"error": "no sampling profiler installed",
                         "hint": "set DMLC_TPU_PROFILE_HZ (launch_"
                                 "local(profile_hz=...)) or call "
                                 "obs.profile.install()"},
                        code=404)
                else:
                    q = parse_qs(url.query)
                    raw_s = q.get("seconds", [None])[0]
                    raw_hz = q.get("hz", [None])[0]
                    if raw_s is None:
                        self._send_json(prof.to_dict())
                    else:
                        # the handler thread sleeps for the burst
                        # window — same clamp as /trace?seconds=N
                        seconds = max(0.0, min(float(raw_s),
                                               MAX_TRACE_CAPTURE_S))
                        hz = float(raw_hz) if raw_hz else None
                        self._send_json(prof.burst(seconds, hz=hz))
            elif url.path == "/rpc":
                self._send_json(_rpc.view())
            elif url.path.startswith("/pages/"):
                self._serve_page(owner, url.path[len("/pages/"):])
            else:
                self._send_json({"error": "unknown endpoint",
                                 "endpoints": ["/metrics",
                                               "/metrics.json",
                                               "/healthz", "/stacks",
                                               "/trace?seconds=N",
                                               "/history", "/gang",
                                               "/tenants", "/slo",
                                               "/shuffle",
                                               "/analyze",
                                               "/control[?last=N]",
                                               "/profile?seconds=N"
                                               "&hz=M",
                                               "/rpc",
                                               "/pages/<entry>"]},
                                code=404)
        except Exception as e:  # noqa: BLE001 — a scrape must never
            try:                # take down the serving thread
                self._send_json({"error": repr(e)}, code=500)
            except Exception:  # noqa: BLE001 — client went away
                pass
        finally:
            ctx = self._rpc_ctx
            if ctx is not None:
                t1 = time.perf_counter()
                arrival = getattr(self, "_rpc_arrival", self._rpc_t0)
                sent = self._rpc_sent if self._rpc_sent is not None \
                    else t1
                verb = url.path.lstrip("/").split("/", 1)[0] or "/"
                _rpc.record_server_span(
                    verb, _rpc.serialize(ctx), arrival, t1 - arrival,
                    args={
                        "peer": str(self.client_address[0]),
                        "queue_us": round(
                            (self._rpc_t0 - arrival) * 1e6, 1),
                        "handle_us": round(
                            (sent - self._rpc_t0) * 1e6, 1),
                        "write_us": round((t1 - sent) * 1e6, 1),
                    })


class StatusServer:
    """One daemon-thread HTTP status server for this process."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: Optional[MetricsRegistry] = None,
                 pages_root: Optional[str] = None):
        self.registry = registry if registry is not None else REGISTRY
        # /pages serves THIS store's committed entries (None = the
        # process default store, resolved per request so env-driven
        # per-rank roots apply)
        self._pages_root = pages_root
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.status_server = self
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self.started_s = time.time()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dmlc_tpu.obs.StatusServer")
        self._thread.start()
        # /analyze wire-counter scoping: (epoch, closing counters of
        # the PREVIOUS epoch, baseline used for this epoch) — see
        # analyze_verdict()
        self._analyze_lock = threading.Lock()
        self._analyze_prev = None
        # the port is itself telemetry: a merged gang snapshot tells
        # the reader where each rank can be curled
        self.registry.gauge("obs.serve_port").set(self.port)

    def pages_store(self):
        """The page store /pages serves from: the explicit
        ``pages_root``, else the process default store (hydrated
        remote blocks live there)."""
        from dmlc_tpu.io.pagestore import PageStore
        if self._pages_root is not None:
            return PageStore.at(self._pages_root)
        return PageStore.default()

    def analyze_verdict(self) -> Optional[Dict[str, Any]]:
        """The /analyze payload: attribute the last completed epoch of
        the first live pipeline collector. Wire-side counters
        (objstore/pagestore) are process-cumulative in the registry, so
        they are DELTA-scoped here against the counters seen when the
        previous epoch closed — earlier remote work (a cold hydration
        configs ago) must not flip a purely local epoch's verdict to
        wire-bound. The very first call has no baseline and reads
        cumulative counters; within one epoch, repeated polls reuse the
        same baseline so the verdict is stable."""
        from dmlc_tpu.obs import analyze as _an
        snap = self.registry.snapshot()
        pipeline = next(
            (v for k, v in sorted(
                (snap.get("collectors") or {}).items())
             if k.startswith("pipeline") and v), None)
        if pipeline is None:
            return None
        counters = dict(snap.get("counters") or {})
        epoch = pipeline.get("epoch")
        with self._analyze_lock:
            prev = self._analyze_prev
            if prev is None:
                baseline = None
                self._analyze_prev = (epoch, counters, None)
            elif epoch != prev[0]:
                baseline = prev[1]
                self._analyze_prev = (epoch, counters, baseline)
            else:
                baseline = prev[2]
        if baseline:
            snap = dict(snap)
            snap["counters"] = {
                k: (v - baseline[k] if isinstance(v, (int, float))
                    and isinstance(baseline.get(k), (int, float))
                    else v)
                for k, v in counters.items()}
        return _an.attribute(pipeline, metrics=snap)

    def health(self) -> Dict[str, Any]:
        from dmlc_tpu.obs import trace as _trace
        from dmlc_tpu.obs import watchdog as _watchdog
        from dmlc_tpu.obs.metrics import worker_rank
        wd = _watchdog.active()
        return {
            "ok": True,
            "pid": os.getpid(),
            "rank": worker_rank(),
            "uptime_s": round(time.time() - self.started_s, 3),
            "tracing": _trace.active() is not None,
            "watchdog": {
                "installed": wd is not None,
                "threshold_s": wd.threshold_s if wd else None,
                "reports": len(wd.reports) if wd else 0,
            },
            "waits": _watchdog.current_waits(),
        }

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "StatusServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_server: Optional[StatusServer] = None


def serve(port: int = 0, host: str = "127.0.0.1",
          registry: Optional[MetricsRegistry] = None) -> StatusServer:
    """Start the process status server (port 0 = OS-assigned; read
    ``.port``). One per process: a second call returns the running
    instance (env/CLI wiring may race module import order)."""
    global _server
    if _server is not None:
        return _server
    _server = StatusServer(port=port, host=host, registry=registry)
    return _server


def serve_if_env() -> Optional[StatusServer]:
    """Gang-worker hook (one line, like trace_if_env): start the status
    server when ``DMLC_TPU_SERVE_PORT`` is set — launch_local's
    ``serve_ports=...`` sets it per worker — else no-op."""
    port = os.environ.get(ENV_SERVE_PORT)
    if not port:
        return None
    try:
        return serve(port=int(port))
    except (ValueError, OSError) as e:
        from dmlc_tpu.obs.log import warn_once
        warn_once("serve-port-failed",
                  f"obs.serve: could not serve on {ENV_SERVE_PORT}="
                  f"{port!r}: {e}", all_ranks=True)
        return None


def shutdown() -> None:
    """Stop the process server started by serve()/serve_if_env()."""
    global _server
    srv, _server = _server, None
    if srv is not None:
        srv.close()


def scrape(port: int, host: str = "127.0.0.1",
           path: str = "/metrics.json",
           timeout_s: float = 5.0) -> Dict[str, Any]:
    """GET one rank's JSON endpoint (stdlib urllib; no deps).

    A resilience seam (site ``obs.scrape``, fail-fast 2-attempt site
    default): one dropped connection does not mark a live rank
    unreachable in the merged gang view. Each poll is a traced RPC
    edge of its own — one operation trace_id per scrape, one client
    span per attempt — so a slow or retried scrape shows up on the
    gang timeline instead of silently inflating ``obs.scrape``."""
    from urllib.request import Request, urlopen

    from dmlc_tpu.resilience.policy import guarded

    def get() -> Dict[str, Any]:
        with _rpc.client_span("scrape", f"{host}:{port}") as call:
            hdrs: Dict[str, str] = {}
            if call is not None:
                _rpc.inject(call.ctx, hdrs)
            with urlopen(Request(f"http://{host}:{port}{path}",
                                 headers=hdrs),
                         timeout=timeout_s) as resp:
                if call is not None:
                    call.note_server(
                        resp.headers.get(_rpc.HANDLE_HEADER))
                return json.load(resp)

    with _rpc.operation("obs.scrape", peer=f"{host}:{port}"):
        return guarded("obs.scrape", get)


def scrape_gang(ports: Optional[List[int]] = None,
                host: str = "127.0.0.1",
                timeout_s: float = 5.0) -> Dict[str, Any]:
    """Scrape every rank's /metrics.json and merge into one gang view
    (merge_snapshots, keyed by rank). ``ports=None`` reads the gang
    list from ``DMLC_TPU_SERVE_PORTS`` — so rank 0 INSIDE a
    launch_local gang can scrape its peers. Unreachable ranks land
    under ``"unreachable"`` instead of failing the merged read (the
    rank you cannot scrape is exactly the one you are diagnosing)."""
    if ports is None:
        raw = os.environ.get(ENV_SERVE_PORTS, "")
        ports = [int(p) for p in raw.split(",") if p.strip()]
    snaps, unreachable = [], {}
    for port in ports:
        try:
            snaps.append(scrape(port, host=host, timeout_s=timeout_s))
        except Exception as e:  # noqa: BLE001 — dead rank stays visible
            unreachable[str(port)] = repr(e)
    merged = merge_snapshots(snaps)
    if unreachable:
        merged["unreachable"] = unreachable
    return merged
