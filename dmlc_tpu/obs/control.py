"""Verdict-driven control plane: the observe→act loop, closed.

PRs 8 and 10 gave the system judgment — schema-pinned bound verdicts
(:mod:`dmlc_tpu.obs.analyze`) with hot-frame evidence — but the
between-epoch :class:`~dmlc_tpu.pipeline.autotune.Autotuner` still
hill-climbed queue depths blind: the pipeline could SAY "parse-bound"
or "credit-limited" and then ignore itself. This module makes the
verdict the policy input. After every completed epoch the
:class:`Controller` attributes the epoch, maps the bound to a knob
*family*, and moves at most ONE knob inside it under the autotuner's
safe-exploration rails (:class:`~dmlc_tpu.pipeline.autotune
.ExplorationRail`: revert on regression, cooldown after a revert,
bounded ×2 steps — generalized here with per-family revert budgets):

- ``parse``-bound  → the parse family (native shard count / worker
  pool / chunk-prefetch depth): more parse-side parallelism;
- ``wire``-bound (a cold pagestore re-fetching) → the wire family:
  raise ``coalesce``, then ``parallel`` GETs, then flip the page
  codec on — automating exactly the per-verdict advice
  docs/remote_io.md documents as manual;
- ``assemble``-bound → the assemble family (staging/prefetch depths,
  bucket-geometry knobs when a caller exposes them);
- ``xfer``-bound → the transfer family (the in-flight device window);
- ``credit-limited`` → **FREEZE every knob** for a cooldown: wall
  rates reflect the credit scheduler, not the pipeline, and a tuner
  that keeps moving is chasing the climate (the exact failure the
  gauge-band machinery was built to name);
- ``consumer``-bound → an explicit no-op record (the pipeline is not
  the bottleneck; moving knobs would be noise).

The observability headline is the **decision ledger**: every decision
— including "freeze" and "no-op" — is an immutable record
``{epoch, verdict_id, tenant, bound, band, evidence, family, knob, old, new,
outcome, reverted}`` kept in a byte-budgeted ring on the
TimeSeriesRing coarsening discipline (old history halves its
resolution, the newest and oldest decisions always survive), so an
operator can always answer "why is this knob at this value" with the
measured evidence that moved it. The ledger is:

- served at ``GET /control`` on every rank's StatusServer,
- rendered by ``obsctl control``,
- emitted as ``control/<family>`` trace instants on the shared
  timeline,
- aggregated gang-wide through the registry collector ``control``
  (numeric leaves ride the PR 8 GangAggregator rollups; ``obsctl
  gang`` prints the per-rank decision/freeze counts),
- attached to flight bundles as ``control.json``.

Wiring mirrors every other obs plane: ``install()`` directly, or
:func:`install_if_env` under ``DMLC_TPU_CONTROL`` (set per worker by
``launch_local(control=True)``). An installed controller ADOPTS every
:class:`~dmlc_tpu.pipeline.graph.CompiledPipeline` that completes an
epoch — the pipeline's "auto" knobs join the controller's families
(stage kind → family) and the pipeline's own Autotuner stands down
(one mover per process; the controller subsumes it on the same
rails). ``scripts/lint.py``'s knob gate confines knob mutation to
``pipeline/autotune.py`` + this module, so no hand-tuned constant can
sneak back in behind the ledger's back.
"""

from __future__ import annotations

import json
import os
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional

from dmlc_tpu.obs.metrics import REGISTRY, MetricsRegistry
from dmlc_tpu.utils.logging import check

__all__ = ["ControlKnob", "DecisionLedger", "Controller",
           "objstore_knobs", "install", "uninstall", "active",
           "install_if_env", "membership_record", "ENV_CONTROL",
           "CONTROL_SCHEMA", "RECORD_KEYS", "FAMILY_FOR_BOUND",
           "FAMILY_FOR_STAGE_KIND"]

ENV_CONTROL = "DMLC_TPU_CONTROL"

# bump when to_dict()'s top-level shape changes incompatibly
CONTROL_SCHEMA = 1

# every ledger record carries exactly these keys (tests/test_control.py
# pins it): the decision, the verdict that caused it, and the measured
# evidence — immutable once appended (a revert is a NEW record, never
# an edit)
RECORD_KEYS = ("epoch", "verdict_id", "tenant", "bound", "band",
               "evidence",
               "family", "knob", "old", "new", "outcome", "reverted")

# verdict bound -> the knob family allowed to move. credit-limited and
# consumer are deliberately absent: the first freezes, the second no-ops.
FAMILY_FOR_BOUND = {
    "parse": "parse",
    "wire": "wire",
    "assemble": "assemble",
    "xfer": "transfer",
}

# pipeline stage kind -> family, for adopted CompiledPipeline knobs
FAMILY_FOR_STAGE_KIND = {
    "parse": "parse",
    "prefetch": "assemble",
    "shard": "assemble",
    "to_device": "transfer",
}

# evidence lines kept per ledger record (the full verdict is served by
# /analyze; the ledger stores the measured lines that moved the knob,
# bounded so the byte budget buys decisions, not prose)
_EVIDENCE_PER_RECORD = 4


class ControlKnob:
    """One integer knob owned by the controller, tagged with its
    family. ``grow`` overrides the default bounded ×2 step (e.g. the
    page codec flips 0 → 6 once instead of ramping). ``owner`` is an
    optional weakref to the object whose lifetime the knob rides
    (an adopted pipeline): a dead owner retires the knob — its
    closures point at closed queues, and trialing it would judge a
    dead pipeline's knob by a live pipeline's throughput."""

    __slots__ = ("name", "family", "get", "set", "lo", "hi", "initial",
                 "_grow", "owner")

    def __init__(self, name: str, family: str, get: Callable[[], int],
                 set: Callable[[int], None], lo: int, hi: int,
                 grow: Optional[Callable[[int], int]] = None,
                 owner: Optional["weakref.ref"] = None):
        check(hi >= lo, f"knob {name}: bad bounds [{lo},{hi}]")
        self.name = name
        self.family = family
        self.get = get
        self.set = set
        self.lo = lo
        self.hi = hi
        self.initial = get()
        self._grow = grow
        self.owner = owner

    def retired(self) -> bool:
        return self.owner is not None and self.owner() is None

    def grow_value(self, cur: int) -> int:
        """The bounded exploration step: at most ×2 per move, clamped
        to [lo, hi]; returns ``cur`` when there is no headroom."""
        if self._grow is not None:
            new = self._grow(cur)
        else:
            new = min(max(cur * 2, self.lo, 1), self.hi)
        return min(max(new, self.lo), self.hi)


class DecisionLedger:
    """Byte-budgeted ring of immutable decision records, on the
    TimeSeriesRing coarsening discipline: when the budget fills, every
    other stored record is dropped across the history (the oldest —
    the run's "why is this knob here at all" anchor — and the NEWEST
    record always survive). Unlike the metrics ring, appends are never
    stride-skipped: every decision lands, old history coarsens."""

    def __init__(self, budget_bytes: int = 64 << 10):
        self.budget_bytes = max(2 << 10, int(budget_bytes))
        self._lock = threading.Lock()
        self._records: List[tuple] = []  # (record, est_bytes)
        self._bytes = 0
        self._offered = 0
        self._coarsenings = 0

    def append(self, record: Dict[str, Any]) -> None:
        est = len(json.dumps(record, default=repr)) + 16
        with self._lock:
            self._offered += 1
            self._records.append((record, est))
            self._bytes += est
            while self._bytes > self.budget_bytes and \
                    len(self._records) >= 8:
                # halve the OLDER history (even indices keep the run's
                # oldest anchor) and always retain the newest record
                kept = self._records[:-1][::2]
                kept.append(self._records[-1])
                self._records = kept
                self._bytes = sum(e for _, e in kept)
                self._coarsenings += 1

    def records(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            recs = [r for r, _ in self._records]
        return recs[-last:] if last else recs

    def to_dict(self, last: Optional[int] = None) -> Dict[str, Any]:
        with self._lock:
            recs = [r for r, _ in self._records]
            out = {
                "offered": self._offered,
                "kept": len(recs),
                "coarsenings": self._coarsenings,
                "approx_bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
            }
        out["records"] = recs[-last:] if last else recs
        return out


def objstore_knobs() -> List[ControlKnob]:
    """The wire family, bound to the live objstore read path
    (``objstore.configure`` — process-global, safely mutable between
    epochs). Ordered by the docs/remote_io.md escalation: coalesce
    more blocks per span, then more parallel GETs, then flip the page
    codec on (0 → level 6 once — compression is a switch, not a
    ramp). This automates the manual WHEN-per-verdict advice."""
    from dmlc_tpu.io import objstore

    def opt(key: str, default: int) -> int:
        v = objstore.options().get(key)
        return int(v) if v is not None else default

    def codec_level() -> int:
        # the EFFECTIVE level: an unset option falls through to the
        # process default (DMLC_TPU_PAGE_CODEC_LEVEL). Reading the raw
        # None as 0 would let a revert write an explicit 0 that
        # silently disables a codec the operator enabled by env.
        v = objstore.options().get("codec_level")
        if v is not None:
            return int(v)
        from dmlc_tpu.io.codec import default_level
        return default_level()

    return [
        ControlKnob("wire.coalesce", "wire",
                    lambda: opt("coalesce", 4),
                    lambda n: objstore.configure(coalesce=n),
                    lo=1, hi=16),
        ControlKnob("wire.parallel", "wire",
                    lambda: opt("parallel", 4),
                    lambda n: objstore.configure(parallel=n),
                    lo=1, hi=16),
        ControlKnob("wire.codec_level", "wire",
                    codec_level,
                    lambda n: objstore.configure(codec_level=n),
                    lo=0, hi=9,
                    grow=lambda cur: 6 if cur == 0 else cur),
    ]


class Controller:
    """The between-epoch controller; see the module docstring.

    Feed it epochs either through :meth:`observe` (a stats snapshot —
    the manual path benches and tests drive) or let an INSTALLED
    controller adopt pipelines automatically (CompiledPipeline calls
    :meth:`observe_pipeline` at each epoch end when one is active).
    """

    def __init__(self, knobs: Optional[List[ControlKnob]] = None, *,
                 revert_tolerance: float = 0.9, cooldown: int = 3,
                 revert_budget: int = 2,
                 ledger_bytes: int = 64 << 10,
                 registry: Optional[MetricsRegistry] = None):
        from dmlc_tpu.pipeline.autotune import ExplorationRail
        self.rail = ExplorationRail(revert_tolerance=revert_tolerance,
                                    cooldown=cooldown,
                                    revert_budget=revert_budget)
        self.ledger = DecisionLedger(ledger_bytes)
        self.registry = registry if registry is not None else REGISTRY
        self._lock = threading.RLock()
        self._knobs: Dict[str, ControlKnob] = {}
        for k in (knobs or []):
            self._knobs[k.name] = k
        # pipelines already adopted (their "auto" knobs joined the
        # families); weak — a closed pipeline drops out on its own.
        # Each gets a MINTED source token (never id(): CPython reuses
        # addresses after GC, and a new pipeline inheriting a dead
        # one's throughput reference would be falsely reverted);
        # dead tokens are pruned with their knobs.
        self._adopted: "weakref.WeakValueDictionary" = \
            weakref.WeakValueDictionary()  # token -> pipeline
        self._minted: set = set()          # every token ever minted
        self._source_seq = 0
        self._counts = {"decisions": 0, "trials": 0, "accepted": 0,
                        "reverted": 0, "freezes": 0, "noops": 0,
                        "exhausted": 0, "discarded": 0}
        # wire-side counters are process-cumulative: delta-scope them
        # per observed epoch AND per source (the serve.py /analyze
        # discipline) so a cold hydration configs ago — or ANOTHER
        # pipeline's traffic — cannot flip a local epoch's verdict to
        # wire-bound
        self._prev_counters: Dict[Any, Dict[str, Any]] = {}
        # recent host-credit gauges fed by the measurement loop
        # (bench.py's memcpy gauge): without them the credit-limited
        # freeze cannot fire — attribute() says so in the band
        self._gauges: List[float] = []
        self._observed = 0  # epochs observed, all sources
        self._metrics_key = self.registry.register(
            "control", self, Controller._collect)

    def note_gauge(self, gauge: float) -> None:
        """Feed one pre-epoch host-credit gauge reading (bench.py's
        memcpy gauge); the next :meth:`observe` without explicit
        ``epoch_gauges`` judges the climate from the recent readings."""
        with self._lock:
            self._gauges.append(float(gauge))
            del self._gauges[:-8]

    # -- knob management

    def add_knobs(self, knobs: List[ControlKnob],
                  prefix: Optional[str] = None) -> None:
        """Register knobs. A name collision (two live pipelines with
        the same stage kinds) is resolved with the stable ``prefix``
        (the adopting pipeline's source token) — "pipe-2.prefetch.
        depth" is attributable across the ledger/obsctl/gang labels,
        an apostrophe suffix would not be."""
        with self._lock:
            self._prune_locked()
            for k in knobs:
                name = k.name
                if name in self._knobs and prefix:
                    name = f"{prefix}.{k.name}"
                while name in self._knobs:
                    name += "'"
                k.name = name
                self._knobs[name] = k

    def _prune_locked(self) -> None:
        """Retire knobs whose owning pipeline is gone: their closures
        point at closed queues, and a pending trial on one would be
        judged by the NEXT pipeline's throughput (and could burn the
        family's revert budget on a ghost). Dead pipelines' source
        state (throughput reference, regime, counter baseline) is
        dropped with them — the maps stay bounded by LIVE pipelines."""
        dead = [name for name, k in self._knobs.items() if k.retired()]
        for name in dead:
            del self._knobs[name]
            self.rail.cancel(name)
        for token in self._minted - set(self._adopted.keys()):
            self._minted.discard(token)
            self.rail.drop_source(token)
            self._prev_counters.pop(token, None)

    def knob_values(self) -> Dict[str, int]:
        with self._lock:
            self._prune_locked()
            return {name: k.get() for name, k in self._knobs.items()}

    def _token_locked(self, pipe) -> tuple:
        """(token, known): the pipeline's minted source token, minting
        one when this is a first sight."""
        for token, p in self._adopted.items():
            if p is pipe:
                return token, True
        self._source_seq += 1
        token = f"pipe-{self._source_seq}"
        self._adopted[token] = pipe
        self._minted.add(token)
        return token, False

    def adopt_pipeline(self, pipe) -> str:
        """Fold a CompiledPipeline's "auto" knobs into the families
        (stage kind → family). Idempotent per pipeline; knobs ride
        the pipeline's lifetime (weak owner) and retire with it.
        Returns the pipeline's source token."""
        with self._lock:
            token, known = self._token_locked(pipe)
            if known:
                return token
            ref = weakref.ref(pipe)
            adopted = []
            # a tenant-admitted pipeline's queue-capacity knobs belong
            # to the multi-tenant scheduler's budget rebalancer — one
            # owner per knob (the same rule that stands the autotuner
            # down when this controller adopts)
            sched_owned = set(getattr(pipe, "scheduler_owned", ()))
            for knob in pipe.knobs():
                if knob.name in sched_owned:
                    continue
                family = FAMILY_FOR_STAGE_KIND.get(knob.stage)
                if family is None:
                    continue
                adopted.append(ControlKnob(
                    knob.name, family, knob.get, knob.set,
                    lo=knob.lo, hi=knob.hi, owner=ref))
            self.add_knobs(adopted, prefix=token)
            return token

    def abandon_pipeline(self, pipe) -> None:
        """Release a pipeline whose epoch hook failed (it fell back to
        its own autotuner, permanently): discard its pending trial
        (value restored), retire its adopted knobs, forget its source
        state. Without this, its unresolved trial would wedge the
        whole controller into no-ops (one pending at a time) and the
        autotuner + controller would both move its knobs."""
        with self._lock:
            token = None
            for t, p in list(self._adopted.items()):
                if p is pipe:
                    token = t
                    del self._adopted[t]
                    break
            if token is None:
                return
            self.rail.discard(source=token)  # restore, no charge
            for name in [n for n, k in self._knobs.items()
                         if k.owner is not None and k.owner() is pipe]:
                del self._knobs[name]
                self.rail.cancel(name)
            self._minted.discard(token)
            self.rail.drop_source(token)
            self._prev_counters.pop(token, None)

    # -- observation

    def observe_pipeline(self, pipe, snapshot: Dict[str, Any]) -> Dict:
        """The CompiledPipeline hook: adopt the pipeline's knobs, then
        decide from its epoch snapshot (source-keyed so two pipelines
        never judge each other's throughput)."""
        token = self.adopt_pipeline(pipe)
        return self.observe(snapshot, source=token)

    def observe(self, snapshot: Dict[str, Any],
                metrics: Optional[Dict[str, Any]] = None,
                epoch_gauges: Optional[List[float]] = None,
                run_band: Optional[str] = None,
                verdict: Optional[Dict[str, Any]] = None,
                source: Any = None) -> Dict[str, Any]:
        """Feed one completed epoch; returns the primary decision
        record appended to the ledger. ``verdict`` overrides the
        attribution (bench embeds the one it already computed);
        otherwise the epoch is attributed from ``metrics`` (default:
        the registry snapshot, wire counters delta-scoped)."""
        from dmlc_tpu.obs import analyze as _analyze
        from dmlc_tpu.pipeline.autotune import (
            epoch_throughput, tier_signature,
        )
        with self._lock:
            self._prune_locked()
            if verdict is None:
                if metrics is None:
                    metrics = self._delta_metrics(source)
                if epoch_gauges is None and run_band is None \
                        and self._gauges:
                    epoch_gauges = self._gauges[-3:]
                verdict = _analyze.attribute(
                    snapshot, metrics=metrics,
                    epoch_gauges=epoch_gauges, run_band=run_band)
            tp = epoch_throughput(snapshot)
            discarded = self.rail.note_regime(tier_signature(snapshot),
                                              source=source)
            if discarded is None and verdict.get("bound") == \
                    "credit-limited":
                # a drained epoch judges NOTHING: its wall throughput
                # is the credit scheduler's, so resolving the pending
                # trial by it would falsely revert a good knob and
                # charge the family's budget — the exact climate-
                # chasing the freeze exists to prevent. Discard like a
                # regime flip: restored, no freeze, no budget charge.
                discarded = self.rail.discard(source)
            if discarded is not None:
                # record orientation is always the TRIAL's (old = the
                # pre-trial value the knob is back at): the outcome
                # says the move was undone, the fields say what it was
                self._counts["discarded"] += 1
                self._append(verdict, family=discarded["group"],
                             knob=discarded["key"],
                             old=discarded["old"],
                             new=discarded["new"],
                             outcome="discarded")
            record = None
            if verdict.get("bound") != "credit-limited":
                resolved = self.rail.observe(tp, source=source)
                if resolved is not None:
                    outcome = resolved["outcome"]  # accepted|reverted
                    self._counts[outcome] += 1
                    rec = self._append(
                        verdict, family=resolved["group"],
                        knob=resolved["key"], old=resolved["old"],
                        new=resolved["new"], outcome=outcome,
                        reverted=outcome == "reverted")
                    if outcome == "reverted":
                        # the reverted epoch ran under the bad value:
                        # no new trial from its stats (the autotuner's
                        # double-count fix, same rail, same reason) —
                        # the revert record IS this epoch's decision
                        record = rec
            if record is None:
                record = self._decide(verdict, source=source)
            self._counts["decisions"] += 1
            self.rail.advance(source)
            self._observed += 1
        return record

    # -- the policy

    def _decide(self, verdict: Dict[str, Any],
                source: Any = None) -> Dict[str, Any]:
        bound = verdict.get("bound")
        if bound == "credit-limited":
            # freeze ALL knobs: the wall rates reflect the credit
            # scheduler; a tuner that keeps moving chases the climate
            self.rail.freeze_all(self._knobs, source=source)
            self._counts["freezes"] += 1
            return self._append(verdict, outcome="freeze")
        family = FAMILY_FOR_BOUND.get(bound)
        if family is None:  # consumer (or an unknown future bound)
            self._counts["noops"] += 1
            return self._append(verdict, outcome="no-op")
        if self.rail.exhausted(family, source=source):
            self._counts["exhausted"] += 1
            return self._append(verdict, family=family,
                                outcome="family-exhausted")
        if self.rail.pending is not None:
            # a trial from another source is mid-flight: one mover per
            # process — record the abstention rather than double-move
            self._counts["noops"] += 1
            return self._append(verdict, family=family, outcome="no-op")
        # eligible knobs: process-global ones (wire options, manual
        # knobs) plus the OBSERVED pipeline's own — another pipeline's
        # knob cannot affect this source's throughput, so trialing it
        # here would void the rail's revert guarantee (the move would
        # be judged by rates it cannot change)
        owner_pipe = self._adopted.get(source) \
            if isinstance(source, str) else None
        for knob in self._knobs.values():
            if knob.family != family or self.rail.frozen(knob.name):
                continue
            if knob.owner is not None and knob.owner() is not owner_pipe:
                continue
            cur = knob.get()
            new = knob.grow_value(cur)
            if new == cur:
                continue  # no headroom on this knob; try the next
            knob.set(new)
            self.rail.begin(knob.name, cur, new, knob.set,
                            group=family, source=source)
            self._counts["trials"] += 1
            return self._append(verdict, family=family, knob=knob.name,
                                old=cur, new=new, outcome="trial")
        self._counts["noops"] += 1
        return self._append(verdict, family=family, outcome="no-op")

    # -- the ledger + its emission surfaces

    def _append(self, verdict: Dict[str, Any],
                family: Optional[str] = None,
                knob: Optional[str] = None,
                old: Optional[int] = None, new: Optional[int] = None,
                outcome: str = "no-op",
                reverted: bool = False) -> Dict[str, Any]:
        record = {
            "epoch": verdict.get("epoch"),
            "verdict_id": verdict.get("verdict_id"),
            # schema-4 verdicts name the tenant whose epoch moved the
            # knob — the ledger answers "who caused this move", not
            # just "what evidence" (None for untenanted pipelines)
            "tenant": verdict.get("tenant"),
            "bound": verdict.get("bound"),
            "band": verdict.get("band"),
            "evidence": list(verdict.get("evidence")
                             or [])[:_EVIDENCE_PER_RECORD],
            "family": family,
            "knob": knob,
            "old": old,
            "new": new,
            "outcome": outcome,
            "reverted": reverted,
        }
        self.ledger.append(record)
        try:  # the decision rides the shared timeline next to the
            # stalls/retries/faults that explain it
            from dmlc_tpu.obs import trace as _trace
            _trace.instant(f"control/{family or outcome}", "control",
                           {"outcome": outcome, "bound": record["bound"],
                            "knob": knob, "old": old, "new": new,
                            "verdict_id": record["verdict_id"]})
        except Exception:  # noqa: BLE001 — telemetry must never raise
            pass
        return record

    def _delta_metrics(self, source: Any = None) -> Dict[str, Any]:
        snap = self.registry.snapshot()
        counters = dict(snap.get("counters") or {})
        # baselines are keyed PER SOURCE: two interleaved pipelines'
        # epochs must each be scoped against their OWN previous epoch,
        # or pipeline A's verdict would carry B's wire bytes
        prev = self._prev_counters.get(source)
        self._prev_counters[source] = counters
        snap = dict(snap)
        if prev:
            snap["counters"] = {
                k: (v - prev[k] if isinstance(v, (int, float))
                    and isinstance(prev.get(k), (int, float)) else v)
                for k, v in counters.items()}
        else:
            # a source's FIRST epoch has no baseline: cumulative
            # counters would blame pre-pipeline traffic (corpus
            # hydration at startup) on this epoch and move a wire
            # knob for it — no wire evidence beats wrong evidence
            snap["counters"] = {}
        return snap

    def _collect(self) -> Dict[str, Any]:
        """The registry collector ("control"): numeric leaves the
        GangAggregator rolls up — every rank's decision cadence on one
        wall-anchored timeline."""
        with self._lock:
            self._prune_locked()
            out: Dict[str, Any] = {"epoch": self._observed}
            out.update(self._counts)
            out["knobs"] = {name: k.get()
                            for name, k in self._knobs.items()}
        return out

    def to_dict(self, last: Optional[int] = None) -> Dict[str, Any]:
        """The /control payload (and the flight bundle's
        control.json)."""
        with self._lock:
            self._prune_locked()
            families: Dict[str, Dict[str, Any]] = {}
            for name, k in self._knobs.items():
                fam = families.setdefault(k.family, {
                    "knobs": [],
                    "reverts": self.rail.reverts_total(k.family)})
                fam["knobs"].append(name)
            knobs = {name: {"family": k.family, "value": k.get(),
                            "initial": k.initial, "lo": k.lo, "hi": k.hi,
                            "frozen": self.rail.frozen(name)}
                     for name, k in self._knobs.items()}
            counts = dict(self._counts)
            epoch = self._observed
        return {
            "schema": CONTROL_SCHEMA,
            "epoch": epoch,
            "counts": counts,
            "families": families,
            "knobs": knobs,
            "ledger": self.ledger.to_dict(last=last),
        }

    def suspend_collector(self) -> None:
        """Unregister the "control" registry collector (detach():
        a suspended controller must not shadow the live one's gang/
        metrics surface — obsctl gang reads ``collectors.control.*``
        by name)."""
        if self._metrics_key is not None:
            self.registry.unregister(self._metrics_key)
            self._metrics_key = None

    def resume_collector(self) -> None:
        if self._metrics_key is None:
            self._metrics_key = self.registry.register(
                "control", self, Controller._collect)

    def close(self) -> None:
        self.suspend_collector()


# ------------------------------------------------------------ module plane

_controller: Optional[Controller] = None


def active() -> Optional[Controller]:
    return _controller


def install(controller: Optional[Controller] = None,
            **kwargs: Any) -> Controller:
    """Install the process controller (idempotent: a second call
    returns the running one). With no argument, a controller over the
    wire-family knobs is built — pipelines join by adoption when they
    complete epochs."""
    global _controller
    if _controller is not None:
        return _controller
    if controller is None:
        controller = Controller(objstore_knobs(), **kwargs)
    controller.resume_collector()  # no-op unless detach()ed before
    _controller = controller
    return _controller


def uninstall() -> None:
    global _controller
    ctl, _controller = _controller, None
    if ctl is not None:
        ctl.close()


def detach() -> Optional[Controller]:
    """Suspend the installed controller WITHOUT closing it — returns
    it so the caller can ``install()`` it back. For probes that must
    run a pipeline under their OWN controller (bench config 16): two
    movers on one pipeline would break the one-mover-per-process
    invariant and judge each other's trials. The suspended
    controller's registry collector is unregistered (so the caller's
    own controller owns the "control" name) and re-registered by
    ``install()``."""
    global _controller
    ctl, _controller = _controller, None
    if ctl is not None:
        ctl.suspend_collector()
    return ctl


def install_if_env() -> Optional[Controller]:
    """Gang-worker hook (one line, like serve_if_env): install the
    controller when ``DMLC_TPU_CONTROL`` is set non-zero —
    ``launch_local(control=True)`` sets it per worker — else no-op."""
    raw = os.environ.get(ENV_CONTROL)
    if not raw or raw.strip() in ("0", "false", "no"):
        return None
    return install()


def membership_record(event: str, gang: str, epoch: int,
                      old_world: int, new_world: int,
                      member: Optional[str] = None,
                      rank: Optional[int] = None,
                      ) -> Optional[Dict[str, Any]]:
    """Land a gang-membership change on the decision ledger
    (rendezvous plane: join/leave/death/reshard). Membership moves are
    DECISIONS about the run's shape — world size is the knob, the
    membership epoch is the evidence — so they share the pinned
    RECORD_KEYS schema and render in ``obsctl control`` next to the
    verdict-driven moves they often explain (a reshard is why the
    next epoch's wire bytes moved). ``verdict_id`` cites the
    membership epoch (``m<epoch>-<gang>``) the way knob records cite
    the verdict that caused them. No-op (returns None) without an
    installed controller — membership is observable on /gang and the
    trace regardless."""
    ctl = active()
    if ctl is None:
        return None
    record = {
        "epoch": int(epoch),
        "verdict_id": f"m{int(epoch)}-{gang}",
        "tenant": None,
        "bound": "membership",
        "band": None,
        "evidence": [f"membership epoch {int(epoch)}: {event}"
                     + (f" of {member}" if member else "")
                     + f", world {int(old_world)} -> "
                       f"{int(new_world)}"
                     + (f" (rank {rank})" if rank is not None
                        else "")],
        "family": "gang",
        "knob": "membership",
        "old": int(old_world),
        "new": int(new_world),
        "outcome": event,
        "reverted": False,
    }
    ctl.ledger.append(record)
    return record
