"""One rate-limited warn channel for the whole repo.

The scattered warn-once patterns (ShardedRowBlockIter's schema-flip
warning, the native-engine-unusable warning, spill-failure degrades)
each kept their own ad-hoc flag, and a multiprocess gang emitted one
copy PER RANK. This module centralizes the policy:

- :func:`warn_once` — emit a key's message at most once per process;
- :func:`warn_limited` — emit a key at most once per ``min_interval_s``
  (for conditions that can recur meaningfully, e.g. spill failures);
- gang deduplication — by default only rank 0 of a launch gang emits
  (``all_ranks=True`` opts out for rank-local facts); suppressed
  messages still count in the ``obs`` metrics registry
  (``log.suppressed`` counter) so they are not silently lost.

Messages flow through :func:`dmlc_tpu.utils.logging.log_warning`, so
``set_log_sink`` hooks and the glog-style formatting keep working.
Every EMITTED warning also lands on the trace timeline as an instant
event (``warn/<key>``, category ``log``) when a recorder is active —
a rate-limited warning is visible right next to the stall or degrade
it explains instead of only in a scrolled-away stderr.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

__all__ = ["warn_once", "warn_limited", "reset"]

_lock = threading.Lock()
_last_emit: Dict[str, float] = {}


def _rank() -> int:
    from dmlc_tpu.obs.metrics import worker_rank
    return worker_rank() or 0


def _suppress_count(reason: str) -> None:
    from dmlc_tpu.obs.metrics import REGISTRY
    REGISTRY.counter(f"log.suppressed.{reason}").inc()


def warn_limited(key: str, msg: str, min_interval_s: float = 60.0,
                 all_ranks: bool = False) -> bool:
    """Emit ``msg`` as a warning unless ``key`` fired within
    ``min_interval_s`` (or this is a nonzero gang rank and the message
    is not rank-local). Returns True when the message was emitted."""
    if not all_ranks and _rank() != 0:
        _suppress_count("rank")
        return False
    now = time.monotonic()
    with _lock:
        last = _last_emit.get(key)
        if last is not None and now - last < min_interval_s:
            _suppress_count("rate")
            return False
        _last_emit[key] = now
    from dmlc_tpu.obs.trace import instant
    instant(f"warn/{key}", "log", {"msg": msg})
    from dmlc_tpu.utils.logging import log_warning
    log_warning(msg)
    return True


def warn_once(key: str, msg: str, all_ranks: bool = False) -> bool:
    """Emit ``msg`` at most once per process for this ``key``."""
    return warn_limited(key, msg, min_interval_s=float("inf"),
                        all_ranks=all_ranks)


def reset() -> None:
    """Forget emission history (tests)."""
    with _lock:
        _last_emit.clear()
