"""dmlc_tpu — a TPU-native data/IO framework with the capabilities of dmlc-core.

Re-designed (not ported) from the reference `trivialfis/dmlc-core`:

- ``dmlc_tpu.utils``    — logging/CHECK, Registry, Parameter, serializer, config
  (reference: include/dmlc/{logging,registry,parameter,serializer,config}.h)
- ``dmlc_tpu.io``       — Stream/SeekStream, URI-dispatched virtual filesystems,
  InputSplit sharding, RecordIO codec, threaded prefetch
  (reference: include/dmlc/{io,recordio,filesystem}.h, src/io/*)
- ``dmlc_tpu.data``     — CSR RowBlock, libsvm/csv/libfm/parquet parsers,
  row iterators (reference: include/dmlc/data.h, src/data/*)
- ``dmlc_tpu.parallel`` — multi-host sharded ingest, device prefetch,
  job launch (reference: tracker/dmlc_tracker/*)
- ``dmlc_tpu.ops``      — JAX/TPU ops over CSR batches (SpMV etc.; new —
  the reference has no device compute, this is the TPU-native seam)
- ``dmlc_tpu.native``   — C++ hot path (parse/split/prefetch) via ctypes
- ``dmlc_tpu.obs``      — unified observability: trace recorder with
  Chrome/Perfetto export, metrics registry, stall watchdog, rate-limited
  log channel (new — see docs/observability.md)
- ``dmlc_tpu.resilience`` — unified retry/backoff policy at the I/O
  seams, deterministic fault injection, elastic gang supervision
  (reference: the tracker's recover/DMLC_NUM_ATTEMPT story — see
  docs/resilience.md)

The hot byte path (sharding, parsing) has two implementations with identical
semantics: a pure-Python golden (always available, used for parity tests) and a
C++ engine (used when built). Parity contract: decimal float parsing is
"nearest double, then cast to float32" on both paths.
"""

__version__ = "0.3.0"

from dmlc_tpu.utils.logging import DMLCError, check, log_info, log_warning, log_error, log_fatal
from dmlc_tpu.utils.registry import Registry
from dmlc_tpu.utils.parameter import Parameter, field, get_env
from dmlc_tpu.io.stream import Stream, SeekStream, MemoryStream
from dmlc_tpu.io.tempdir import TemporaryDirectory
from dmlc_tpu.data.rowblock import RowBlock, RowBlockContainer
from dmlc_tpu.data.parser import Parser
from dmlc_tpu.data.row_iter import RowBlockIter

__all__ = [
    "DMLCError", "check", "log_info", "log_warning", "log_error", "log_fatal",
    "Registry", "Parameter", "field", "get_env",
    "Stream", "SeekStream", "MemoryStream", "TemporaryDirectory",
    "RowBlock", "RowBlockContainer", "Parser", "RowBlockIter",
    "__version__",
]
